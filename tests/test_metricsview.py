"""Metrics time-series backplane (ray_tpu.metricsview).

Store downsampling/eviction, reset-aware windowed queries, histogram
window percentiles, the SLO dual-window burn-rate lifecycle, windowed
OTLP export, the unconditional terminal worker flush, and the live
query -> alert -> bundle loop end to end (state API, job-server REST,
`ray-tpu metrics`/`ray-tpu alerts` CLIs, flight-recorder bundle).

Reference analogs: Prometheus TSDB head-block semantics (PromQL
``increase``/``histogram_quantile``) + the SRE-workbook multiwindow
multi-burn-rate alerting pattern.
"""

import json
import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private.config import Config
from ray_tpu.metricsview import (AGGS, MetricsView, SeriesStore, SloEngine,
                                 SloObjective, parse_quantile,
                                 parse_tag_args, validate_agg)
from ray_tpu.metricsview.slo import FIRING_GAUGE, TRANSITIONS_TOTAL
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import telemetry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LAT = "ray_tpu_serve_request_latency_seconds"
BOUNDS = (0.01, 0.1, 1.0)


def _hist(counts, total_sum, count):
    """Cumulative store-shape histogram value (counts include +Inf)."""
    return {"counts": list(counts), "sum": total_sum, "count": count}


class TestSeriesStore:
    def test_downsample_one_point_per_interval(self):
        store = SeriesStore(interval_s=1.0, max_points=10)
        store.append("g", {}, "gauge", 1.0, 0.1)
        store.append("g", {}, "gauge", 2.0, 0.9)   # same bucket: replaces
        store.append("g", {}, "gauge", 3.0, 1.2)   # next bucket
        hist = store.history("g", window_s=10.0, now=2.0)
        pts = hist["series"][0]["points"]
        assert [v for _age, v in pts] == [2.0, 3.0]
        assert store.stats()["points_total"] == 2

    def test_ring_eviction_accounts_drops(self):
        store = SeriesStore(interval_s=1.0, max_points=3)
        for i in range(6):
            store.append("c", {}, "counter", float(i), float(i))
        st = store.stats()
        assert st["live_points"] == 3
        assert st["points_total"] == 6
        assert st["dropped_total"] == 3
        # Retention window slides: only the newest 3 points answer.
        out = store.query("c", window_s=100.0, agg="last", now=6.0)
        assert out["value"] == 5.0
        assert out["points"] == 3

    def test_max_series_cap_rejects_new_series(self):
        store = SeriesStore(interval_s=1.0, max_points=4, max_series=2)
        store.append("a", {"k": "1"}, "gauge", 1.0, 0.0)
        store.append("a", {"k": "2"}, "gauge", 2.0, 0.0)
        store.append("a", {"k": "3"}, "gauge", 3.0, 0.0)  # over cap
        st = store.stats()
        assert st["series"] == 2
        assert st["dropped_total"] == 1
        # Existing series keep ingesting.
        store.append("a", {"k": "1"}, "gauge", 9.0, 1.5)
        assert store.query("a", 10.0, "last", tags={"k": "1"},
                           now=2.0)["value"] == 9.0

    def test_counter_delta_measures_from_last_reset(self):
        store = SeriesStore(interval_s=1.0, max_points=16)
        for t, v in enumerate([0.0, 5.0, 10.0, 2.0, 4.0]):
            store.append("c", {}, "counter", v, float(t))
        # Reset at t=3 (10 -> 2): the window's increase is 4 - 2.
        assert store.query("c", 10.0, "delta", now=4.0)["value"] == 2.0
        # A single post-reset point alone yields no delta (zero-width).
        store2 = SeriesStore(interval_s=1.0, max_points=16)
        store2.append("c", {}, "counter", 50.0, 0.0)
        store2.append("c", {}, "counter", 1.0, 1.0)
        assert store2.query("c", 10.0, "delta", now=1.0)["value"] == 0.0

    def test_gauge_delta_is_signed(self):
        store = SeriesStore(interval_s=1.0, max_points=16)
        store.append("g", {}, "gauge", 10.0, 0.0)
        store.append("g", {}, "gauge", 4.0, 3.0)
        assert store.query("g", 10.0, "delta", now=3.0)["value"] == -6.0

    def test_baseline_point_before_window_extends_delta(self):
        """PromQL range-vector semantics: the last pre-window point is
        the delta baseline, so a sparse series still answers."""
        store = SeriesStore(interval_s=1.0, max_points=16)
        store.append("c", {}, "counter", 100.0, 0.0)
        store.append("c", {}, "counter", 160.0, 50.0)
        out = store.query("c", 20.0, "delta", now=55.0)
        assert out["value"] == 60.0
        assert out["points"] == 1  # only one point IN the window

    def test_scalar_aggs(self):
        store = SeriesStore(interval_s=1.0, max_points=16)
        for t, v in enumerate([1.0, 3.0, 2.0]):
            store.append("g", {}, "gauge", v, float(t))
        q = lambda agg: store.query("g", 10.0, agg, now=2.0)["value"]
        assert q("avg") == pytest.approx(2.0)
        assert q("min") == 1.0
        assert q("max") == 3.0
        assert q("last") == 2.0

    def test_histogram_window_percentile_from_bucket_deltas(self):
        """p99 answers from the WINDOW's observations: the pre-window
        cumulative state cancels out of the bucket delta."""
        store = SeriesStore(interval_s=1.0, max_points=64)
        # 100 old observations, all fast (cumulative at t=0).
        store.append("h", {}, "histogram",
                     _hist([100, 100, 100, 100], 0.5, 100), 0.0,
                     bounds=BOUNDS)
        # Window adds 90 fast + 10 slow (between 0.1 and 1.0).
        store.append("h", {}, "histogram",
                     _hist([190, 190, 200, 200], 6.0, 200), 100.0,
                     bounds=BOUNDS)
        p99 = store.query("h", 60.0, "p99", now=100.0)["value"]
        # Window distribution: 90 in (0, 0.01], 10 in (0.1, 1.0].
        assert 0.1 < p99 <= 1.0
        p50 = store.query("h", 60.0, "p50", now=100.0)["value"]
        assert p50 <= 0.01
        # Window avg uses the sum/count delta, not lifetime.
        avg = store.query("h", 60.0, "avg", now=100.0)["value"]
        assert avg == pytest.approx(5.5 / 100)
        assert store.query("h", 60.0, "delta", now=100.0)["value"] == 100.0

    def test_histogram_restart_exports_post_restart_state(self):
        store = SeriesStore(interval_s=1.0, max_points=64)
        store.append("h", {}, "histogram",
                     _hist([50, 60, 70, 70], 9.0, 70), 0.0, bounds=BOUNDS)
        # Count shrank: source restarted; window = post-restart state.
        store.append("h", {}, "histogram",
                     _hist([5, 6, 7, 7], 0.9, 7), 10.0, bounds=BOUNDS)
        assert store.query("h", 60.0, "delta", now=10.0)["value"] == 7.0

    def test_multi_series_combination_rules(self):
        store = SeriesStore(interval_s=1.0, max_points=16)
        for w, incr in (("a", 10.0), ("b", 30.0)):
            store.append("c", {"w": w}, "counter", 0.0, 0.0)
            store.append("c", {"w": w}, "counter", incr, 10.0)
        # Counter deltas SUM across series (cluster total)...
        assert store.query("c", 20.0, "delta", now=10.0)["value"] == 40.0
        # ...and a tag filter narrows to one series.
        assert store.query("c", 20.0, "delta", tags={"w": "a"},
                           now=10.0)["value"] == 10.0
        # Gauges average; min/max take extremes.
        for w, v in (("a", 2.0), ("b", 6.0)):
            store.append("g", {"w": w}, "gauge", v, 0.0)
        assert store.query("g", 10.0, "avg", now=1.0)["value"] == 4.0
        assert store.query("g", 10.0, "min", now=1.0)["value"] == 2.0
        assert store.query("g", 10.0, "max", now=1.0)["value"] == 6.0

    def test_history_sparkline_shape_and_cap(self):
        store = SeriesStore(interval_s=1.0, max_points=600)
        for i in range(100):
            store.append("g", {}, "gauge", float(i), float(i))
        out = store.history("g", window_s=1000.0, now=100.0, max_points=10)
        pts = out["series"][0]["points"]
        assert len(pts) <= 11  # strided + preserved tail
        assert pts[-1][1] == 99.0
        ages = [a for a, _v in pts]
        assert ages == sorted(ages, reverse=True)  # oldest first

    def test_history_histogram_renders_interval_average(self):
        store = SeriesStore(interval_s=1.0, max_points=16)
        store.append("h", {}, "histogram", _hist([10, 10, 10, 10], 0.1, 10),
                     0.0, bounds=BOUNDS)
        store.append("h", {}, "histogram", _hist([10, 10, 20, 20], 5.1, 20),
                     1.0, bounds=BOUNDS)
        pts = store.history("h", 10.0, now=1.0)["series"][0]["points"]
        # Second row: 10 new observations totalling 5.0s -> 0.5 avg.
        assert pts[-1][1] == pytest.approx(0.5)

    def test_window_rows_for_delta_export(self):
        store = SeriesStore(interval_s=1.0, max_points=16)
        store.append("c", {}, "counter", 5.0, 0.0)
        store.append("c", {}, "counter", 25.0, 10.0)
        store.append("g", {}, "gauge", 7.0, 10.0)
        store.append("h", {}, "histogram", _hist([1, 1, 1, 1], 0.001, 1),
                     0.0, bounds=BOUNDS)
        store.append("h", {}, "histogram", _hist([1, 1, 101, 101], 30.0, 101),
                     10.0, bounds=BOUNDS)
        rows = {name: (mtype, value, bounds) for name, _t, mtype, value,
                bounds in store.window_rows(8.0, now=10.0)}
        assert rows["c"][1] == 20.0           # window increase
        assert rows["g"][1] == 7.0            # latest value
        per = rows["h"][1]["per"]
        assert per == [0.0, 0.0, 100.0, 0.0]  # window's per-bucket delta
        assert rows["h"][1]["count"] == 100
        assert rows["h"][2] == list(BOUNDS)

    def test_validate_agg_and_quantile_parse(self):
        assert all(validate_agg(a) for a in AGGS)
        assert validate_agg("p99") and validate_agg("p99.9")
        assert not validate_agg("sum") and not validate_agg("p0")
        assert parse_quantile("p75") == pytest.approx(0.75)
        assert parse_quantile("avg") is None

    def test_parse_tag_args(self):
        assert parse_tag_args(("a=1", "b = x ")) == {"a": "1", "b": "x"}
        assert parse_tag_args(()) is None
        with pytest.raises(ValueError):
            parse_tag_args(("nokey",))


class TestSloEngine:
    def _store_with_latency(self):
        store = SeriesStore(interval_s=1.0, max_points=600)
        # Healthy baseline: all observations fast.
        store.append(LAT, {}, "histogram", _hist([100, 100, 100, 100],
                                                 0.5, 100), 0.0,
                     bounds=BOUNDS)
        store.append(LAT, {}, "histogram", _hist([200, 200, 200, 200],
                                                 1.0, 200), 10.0,
                     bounds=BOUNDS)
        return store

    def _objective(self, **kw):
        base = dict(name="serve-p99", metric=LAT, agg="p99", op="<",
                    threshold=0.5, fast_window_s=30.0, slow_window_s=60.0,
                    pending_for_s=0.0, cooldown_s=20.0)
        base.update(kw)
        return SloObjective(**base)

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            self._objective(op="==")
        with pytest.raises(ValueError):
            self._objective(agg="p200")
        with pytest.raises(ValueError):
            self._objective(fast_window_s=60.0, slow_window_s=30.0)
        spec = self._objective().spec()
        assert SloObjective.from_spec(spec).spec() == spec
        # from_spec drops unknown keys (forward-compatible payloads).
        spec["bogus"] = 1
        assert SloObjective.from_spec(spec).name == "serve-p99"

    def test_full_alert_lifecycle(self):
        """ok -> pending -> firing -> resolved -> ok, each edge driven
        by logical-time evaluation over real bucket-delta burn rates."""
        store = self._store_with_latency()
        events = []
        eng = SloEngine(store, event_sink=lambda st, e: events.append((st, e)))
        eng.set_objectives([self._objective()])

        assert eng.evaluate(now=10.0) == []   # healthy: stays ok
        st = eng.status(now=10.0)["objectives"][0]
        assert st["state"] == "ok" and st["burn_fast"] == 0.0

        # Latency spike: 100 new observations, all over 0.5s.
        store.append(LAT, {}, "histogram", _hist([200, 200, 200, 300],
                                                 250.0, 300), 20.0,
                     bounds=BOUNDS)
        fired = eng.evaluate(now=20.0)
        assert [t["to"] for t in fired] == ["pending"]
        assert fired[0]["burn_fast"] >= 1.0
        # Slow window burns too -> firing on the next pass.
        fired = eng.evaluate(now=21.0)
        assert [t["to"] for t in fired] == ["firing"]
        assert eng.status(now=21.0)["firing"] == 1

        # Recovery: fresh fast observations push the spike out of the
        # fast window (baseline extends from the spike point).
        store.append(LAT, {}, "histogram", _hist([400, 400, 400, 500],
                                                 251.0, 500), 60.0,
                     bounds=BOUNDS)
        fired = eng.evaluate(now=60.0)
        assert [t["to"] for t in fired] == ["resolved"]
        # Cooldown holds resolved...
        assert eng.evaluate(now=70.0) == []
        # ...then decays to ok.
        fired = eng.evaluate(now=81.0)
        assert [t["to"] for t in fired] == ["ok"]

        # Every transition hit the export sink with the objective's
        # identity and burn rates attached.
        assert [e["to"] for _st, e in events] == \
            ["pending", "firing", "resolved", "ok"]
        assert all(st == "EXPORT_ALERT" for st, _e in events)
        assert all(e["objective"] == "serve-p99" for _st, e in events)
        assert all("_t" not in e for _st, e in events)

        # Transition ring renders with ages for `ray-tpu alerts`.
        trans = eng.status(now=90.0)["transitions"]
        assert len(trans) == 4
        assert trans[-1]["age_s"] == pytest.approx(9.0, abs=0.1)

    def test_reburn_during_cooldown_returns_to_firing(self):
        store = self._store_with_latency()
        eng = SloEngine(store)
        eng.set_objectives([self._objective()])
        store.append(LAT, {}, "histogram", _hist([200, 200, 200, 300],
                                                 250.0, 300), 20.0,
                     bounds=BOUNDS)
        eng.evaluate(now=20.0)
        eng.evaluate(now=21.0)
        store.append(LAT, {}, "histogram", _hist([400, 400, 400, 500],
                                                 251.0, 500), 60.0,
                     bounds=BOUNDS)
        eng.evaluate(now=60.0)  # resolved
        # Second spike inside the cooldown: same incident, back to firing
        # without a fresh pending dwell.
        store.append(LAT, {}, "histogram", _hist([400, 400, 400, 700],
                                                 500.0, 700), 70.0,
                     bounds=BOUNDS)
        fired = eng.evaluate(now=70.0)
        assert [t["to"] for t in fired] == ["firing"]

    def test_pending_blip_returns_to_ok(self):
        store = self._store_with_latency()
        eng = SloEngine(store)
        # Long pending dwell: the blip may not fire.
        eng.set_objectives([self._objective(pending_for_s=30.0)])
        store.append(LAT, {}, "histogram", _hist([200, 200, 200, 300],
                                                 250.0, 300), 20.0,
                     bounds=BOUNDS)
        fired = eng.evaluate(now=20.0)
        assert [t["to"] for t in fired] == ["pending"]
        # Dwell not reached; then the fast window recovers.
        assert eng.evaluate(now=25.0) == []
        store.append(LAT, {}, "histogram", _hist([400, 400, 400, 500],
                                                 251.0, 500), 55.0,
                     bounds=BOUNDS)
        fired = eng.evaluate(now=55.0)
        assert [t["to"] for t in fired] == ["ok"]

    def test_scalar_objective_binary_breach(self):
        store = SeriesStore(interval_s=1.0, max_points=64)
        store.append("ray_tpu_train_goodput_ratio", {}, "gauge", 0.9, 0.0)
        eng = SloEngine(store)
        eng.set_objectives([SloObjective(
            name="goodput", metric="ray_tpu_train_goodput_ratio",
            agg="avg", op=">=", threshold=0.5, fast_window_s=10.0,
            slow_window_s=20.0)])
        assert eng.evaluate(now=1.0) == []
        store.append("ray_tpu_train_goodput_ratio", {}, "gauge", 0.1, 15.0)
        fired = eng.evaluate(now=15.0)
        assert [t["to"] for t in fired] == ["pending"]
        st = eng.status(now=15.0)["objectives"][0]
        assert st["burn_fast"] == 1.0  # binary breach, not a ratio

    def test_no_data_objective_stays_ok(self):
        eng = SloEngine(SeriesStore())
        eng.set_objectives([self._objective(metric="ray_tpu_nope")])
        assert eng.evaluate(now=5.0) == []
        st = eng.status(now=5.0)["objectives"][0]
        assert st["state"] == "ok" and st["no_data"] is True

    def test_state_survives_objective_replacement(self):
        store = self._store_with_latency()
        eng = SloEngine(store)
        eng.set_objectives([self._objective()])
        store.append(LAT, {}, "histogram", _hist([200, 200, 200, 300],
                                                 250.0, 300), 20.0,
                     bounds=BOUNDS)
        eng.evaluate(now=20.0)
        eng.evaluate(now=21.0)
        assert eng.status(now=21.0)["firing"] == 1
        # Re-set with the same name (new threshold): state carries over.
        eng.set_objectives([self._objective(threshold=0.4)])
        assert eng.status(now=22.0)["firing"] == 1
        # A different name starts fresh.
        eng.set_objectives([self._objective(name="other")])
        assert eng.status(now=23.0)["firing"] == 0


class TestMetricsViewUnit:
    def test_refresh_throttles_to_interval(self):
        view = MetricsView(interval_s=5.0)
        assert view.refresh(now=100.0) is True
        assert view.refresh(now=101.0) is False   # inside the interval
        assert view.refresh(now=106.0) is True
        assert view.refresh(now=106.5, force=True) is True

    def test_query_rejects_unknown_agg(self):
        view = MetricsView(interval_s=1.0)
        with pytest.raises(ValueError, match="unknown agg"):
            view.query("x", agg="sum")

    def test_bundle_snapshot_caps_series(self):
        view = MetricsView(interval_s=1.0)
        for i in range(8):
            view.store.append(f"s{i}", {}, "gauge", float(i), 0.0)
        snap = view.bundle_snapshot(max_series=3, max_points=5)
        assert len(snap["series"]) == 3
        assert snap["stats"]["series"] == 8


class TestTerminalFlush:
    """Worker-teardown metrics contract: the terminal push is
    UNCONDITIONAL.  The dirty-flag-gated task-done flush has a teardown
    race — a sample recorded after the flag check (teardown hooks,
    executor-shutdown stragglers, atexit-adjacent user code) has no next
    completion to retry on — so shutdown must push regardless."""

    class _FakeWorkerRt:
        class _Id(bytes):
            pass

        def __init__(self):
            self.sent = []
            self.worker_id = self._Id(b"\xab\xcd")

        def send(self, frame):
            self.sent.append(frame)

    @pytest.fixture()
    def worker_rt(self, monkeypatch):
        from ray_tpu._private import runtime as rt_mod
        metrics_mod._reset_for_tests()
        rt = self._FakeWorkerRt()
        monkeypatch.setattr(rt_mod, "current_runtime", lambda: rt)
        monkeypatch.setattr(rt_mod, "driver_runtime", lambda: None)
        yield rt
        metrics_mod._reset_for_tests()

    def test_terminal_flush_pushes_clean_registry(self, worker_rt):
        telemetry.inc("ray_tpu_data_rows_total", 3.0,
                      tags={"operator": "map"})
        # The race's post-state: flag observed clean while the registry
        # holds the sample (recorded between check and exit).
        metrics_mod._dirty = False
        metrics_mod.flush_on_task_done()
        assert worker_rt.sent == []      # gated flush drops it...
        metrics_mod.flush_terminal()
        assert len(worker_rt.sent) == 1  # ...terminal flush does not
        frame = worker_rt.sent[0]
        assert frame.method == "metrics_push"
        source_id, snaps = frame.args
        assert source_id == worker_rt.worker_id.hex()
        rows = [(s["name"], sample)
                for s in snaps for sample in s["samples"]]
        assert any(n == "ray_tpu_data_rows_total" and v == 3.0
                   for n, (_sn, _tags, v) in rows)

    def test_task_done_flush_still_gated_and_retries(self, worker_rt):
        metrics_mod._dirty = False
        metrics_mod.flush_on_task_done()
        assert worker_rt.sent == []  # metric-free task: only a bool check
        telemetry.inc("ray_tpu_data_rows_total", tags={"operator": "map"})
        assert metrics_mod._dirty is True
        metrics_mod.flush_on_task_done()
        assert len(worker_rt.sent) == 1
        assert metrics_mod._dirty is False

    def test_worker_teardown_calls_terminal_flush(self):
        """The recv-loop teardown must use the unconditional flush, not
        the dirty-gated one (the regression this class guards)."""
        import inspect

        from ray_tpu._private import worker as worker_mod
        src = inspect.getsource(worker_mod)
        assert "flush_terminal" in src


class TestOtlpWindowedExport:
    def test_windowed_export_requires_driver(self):
        with pytest.raises(RuntimeError, match="driver runtime"):
            metrics_mod.export_otlp_json("/tmp/_nope.json", window_s=60.0)

    def test_roundtrip_live_and_windowed(self, ray_start_isolated,
                                         tmp_path):
        telemetry.inc("ray_tpu_data_rows_total", 5.0,
                      tags={"operator": "map"})
        telemetry.set_gauge("ray_tpu_serve_replicas", 3.0,
                            tags={"deployment": "d"})
        telemetry.observe(LAT, 0.02, tags={"deployment": "d"})
        telemetry.observe(LAT, 0.7, tags={"deployment": "d"})

        # Live export: cumulative temporality.
        live = tmp_path / "live.json"
        metrics_mod.export_otlp_json(str(live))
        doc = json.loads(live.read_text())
        metrics = {m["name"]: m for m in
                   doc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]}
        row = metrics["ray_tpu_data_rows_total"]["sum"]
        assert row["isMonotonic"] and row["aggregationTemporality"] == 2
        assert any(p["asDouble"] == 5.0 for p in row["dataPoints"])
        assert metrics["ray_tpu_serve_replicas"]["gauge"]["dataPoints"]
        h = metrics[LAT]["histogram"]
        assert h["aggregationTemporality"] == 2
        hp = h["dataPoints"][0]
        assert int(hp["count"]) == 2
        assert hp["sum"] == pytest.approx(0.72)
        assert len(hp["bucketCounts"]) == len(hp["explicitBounds"]) + 1

        # Windowed export answers from the head store with DELTA
        # temporality.
        from ray_tpu._private import runtime as rt_mod
        rt_mod.driver_runtime().metricsview.refresh(force=True)
        win = tmp_path / "window.json"
        metrics_mod.export_otlp_json(str(win), window_s=120.0)
        doc = json.loads(win.read_text())
        metrics = {m["name"]: m for m in
                   doc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]}
        assert metrics["ray_tpu_data_rows_total"]["sum"][
            "aggregationTemporality"] == 1
        h = metrics[LAT]["histogram"]
        assert h["aggregationTemporality"] == 1
        assert int(h["dataPoints"][0]["count"]) == 2


@pytest.fixture()
def metricsview_cluster():
    """Cluster with a near-continuous ingest interval so consecutive
    API reads drive distinct SLO evaluation passes."""
    prev = Config.get("metricsview_interval_s")
    Config.set("metricsview_interval_s", 0.05)
    metrics_mod._reset_for_tests()  # drop prior tests' driver-side samples
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()
    Config.set("metricsview_interval_s", prev)


class TestLiveBackplane:
    """The acceptance path: live history answers windowed queries, an
    injected latency spike walks one objective through its lifecycle,
    and every surface (state API, REST, CLI, export events, bundle)
    shows it."""

    @pytest.fixture()
    def server(self, metricsview_cluster):
        from ray_tpu.job_submission.manager import JobManager
        from ray_tpu.job_submission.server import JobServer
        server = JobServer(JobManager(), port=0)
        server.rt = metricsview_cluster
        yield server
        server.stop()

    def _cli(self, args):
        from click.testing import CliRunner

        from ray_tpu.scripts.cli import cli
        return CliRunner().invoke(cli, args)

    def test_query_alert_lifecycle_all_surfaces(self, server, tmp_path):
        from ray_tpu.util import state as rstate
        rt = server.rt
        addr = server.address

        # -- seed healthy latency history ------------------------------
        for _ in range(20):
            telemetry.observe(LAT, 0.01, tags={"deployment": "d"})
        out = rstate.metrics_query(LAT, window_s=120.0, agg="p99")
        assert out["value"] is not None and out["value"] < 0.5
        assert out["series"] >= 1

        # -- objective: p99 < 0.5 with a short fast window -------------
        assert rstate.slo_set([{
            "name": "serve-p99", "metric": LAT, "agg": "p99",
            "op": "<", "threshold": 0.5, "fast_window_s": 2.0,
            "slow_window_s": 4.0, "pending_for_s": 0.0,
            "cooldown_s": 0.2}]) == 1
        assert rstate.slo_list()[0]["name"] == "serve-p99"
        st = rstate.alerts()
        assert st["objectives"][0]["state"] == "ok"

        # -- inject the spike ------------------------------------------
        for _ in range(50):
            telemetry.observe(LAT, 2.0, tags={"deployment": "d"})
        saw = set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = rstate.alerts()
            saw.add(st["objectives"][0]["state"])
            if "firing" in saw:
                break
            time.sleep(0.1)
        assert "firing" in saw, st

        # p99 over the window now reports the spike.
        spike = rstate.metrics_query(LAT, window_s=120.0, agg="p99")
        assert spike["value"] > 0.5

        # -- recovery: spike ages out of the 2 s fast window -----------
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            telemetry.observe(LAT, 0.01, tags={"deployment": "d"})
            st = rstate.alerts()
            saw.add(st["objectives"][0]["state"])
            if {"resolved", "ok"} & saw:
                break
            time.sleep(0.25)
        assert {"resolved", "ok"} & saw, st
        trans = [t["to"] for t in st["transitions"]]
        assert "pending" in trans and "firing" in trans

        # -- history + series surfaces ---------------------------------
        hist = rstate.metrics_history(LAT, window_s=300.0)
        assert hist["series"] and hist["series"][0]["points"]
        assert LAT in rstate.metrics_series()

        # -- REST surface (addr already carries the scheme) ------------
        import urllib.request
        with urllib.request.urlopen(
                f"{addr}/api/cluster/metrics/query?name={LAT}"
                f"&window=120&agg=p99") as r:
            doc = json.loads(r.read())
        assert doc["value"] > 0.5
        with urllib.request.urlopen(f"{addr}/api/cluster/alerts") as r:
            doc = json.loads(r.read())
        assert doc["objectives"][0]["objective"] == "serve-p99"
        assert any(t["to"] == "firing" for t in doc["transitions"])
        with urllib.request.urlopen(
                f"{addr}/api/cluster/metrics/history?name={LAT}") as r:
            assert json.loads(r.read())["series"]

        # -- CLI surfaces ----------------------------------------------
        r = self._cli(["metrics", "query", "--address", addr,
                       "--window", "120", "--agg", "p99", LAT])
        assert r.exit_code == 0, r.output
        assert "p99 over 120s" in r.output
        r = self._cli(["metrics", "history", "--address", addr, LAT])
        assert r.exit_code == 0, r.output
        r = self._cli(["metrics", "series", "--address", addr])
        assert r.exit_code == 0 and LAT in r.output
        r = self._cli(["alerts", "--address", addr])
        assert r.exit_code == 0, r.output
        assert "serve-p99" in r.output
        assert "firing" in r.output  # transition log carries the edge
        r = self._cli(["slo", "list", "--address", addr])
        assert r.exit_code == 0 and "serve-p99" in r.output
        spec_file = tmp_path / "slo.json"
        spec_file.write_text(json.dumps([{
            "name": "second", "metric": LAT, "agg": "avg",
            "op": "<", "threshold": 10.0}]))
        r = self._cli(["slo", "set", "--address", addr, str(spec_file)])
        assert r.exit_code == 0, r.output
        assert "registered 1 objective" in r.output

        # -- export-event stream + alert telemetry ---------------------
        with open(rt.export_events._path) as f:
            alert_events = [json.loads(line) for line in f
                            if '"EXPORT_ALERT"' in line]
        assert any(e["to"] == "firing" and e["objective"] == "serve-p99"
                   for e in alert_events)
        prom = metrics_mod.prometheus_text()
        assert TRANSITIONS_TOTAL in prom
        assert FIRING_GAUGE in prom
        assert "ray_tpu_metricsview_points_total" in prom

        # -- flight-recorder bundle carries the alert story ------------
        bundle = rstate.debug_dump("metricsview-test")
        with open(os.path.join(bundle, "alerts.json")) as f:
            alerts_doc = json.load(f)
        assert alerts_doc["objectives"]
        assert any(t["to"] == "firing" for t in alerts_doc["transitions"])
        with open(os.path.join(bundle, "metrics_history.json")) as f:
            hist_doc = json.load(f)
        assert LAT in hist_doc["series"]
        with open(os.path.join(bundle, "manifest.json")) as f:
            manifest = json.load(f)
        assert {"alerts.json", "metrics_history.json"} <= \
            set(manifest["contents"])

    def test_dashboard_http_surface(self, metricsview_cluster):
        import urllib.error
        import urllib.request

        from ray_tpu.dashboard.server import DashboardServer
        telemetry.observe(LAT, 0.05, tags={"deployment": "d"})
        dash = DashboardServer(metricsview_cluster, port=0)
        try:
            base = f"http://127.0.0.1:{dash.port}"
            with urllib.request.urlopen(
                    f"{base}/api/metrics/history?name={LAT}") as r:
                doc = json.loads(r.read())
            assert doc["name"] == LAT
            with urllib.request.urlopen(
                    f"{base}/api/metrics/query?name={LAT}&window=60"
                    f"&agg=avg") as r:
                assert "value" in json.loads(r.read())
            with urllib.request.urlopen(f"{base}/api/alerts") as r:
                assert "objectives" in json.loads(r.read())
            # Missing ?name= and bad aggs are 400s, not 500s.
            for bad in ("/api/metrics/history",
                        f"/api/metrics/query?name={LAT}&agg=bogus"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(base + bad)
                assert ei.value.code == 400
        finally:
            dash.stop()


class TestGoodputPolicyOnBackplane:
    """Satellite: the autoscaler's sag window rides the shared store."""

    def test_policy_window_is_a_series_store(self):
        from ray_tpu.autoscaler import (GoodputAutoscalePolicy,
                                        GoodputPolicyConfig)
        pol = GoodputAutoscalePolicy(GoodputPolicyConfig(window_s=30.0))
        assert isinstance(pol._window, SeriesStore)
        pol.observe_goodput({"productive_s": 1.0, "total_s": 10.0}, now=0.0)
        pol.observe_goodput({"productive_s": 2.0, "total_s": 20.0}, now=5.0)
        assert pol.windowed_goodput() == pytest.approx(0.1)
        # Tracker restart: reset-aware delta -> no phantom window.
        pol.observe_goodput({"productive_s": 0.5, "total_s": 1.0}, now=10.0)
        assert pol.windowed_goodput() is None


class TestFastBenchSmoke:
    def test_fast_bench_end_to_end(self, tmp_path):
        """`bench.py --spec metrics --fast` wired into tier-1 as a
        smoke, in a subprocess with a hard wall bound."""
        import subprocess

        out = str(tmp_path / "BENCH_metrics.json")
        code = (
            "import bench, json\n"
            f"doc = bench.bench_metrics(fast=True, out_path={out!r})\n"
            "print('BENCH_PASS', doc['pass'])\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="", XLA_FLAGS="")
        proc = subprocess.run(
            [sys.executable, "-u", "-c", code], cwd=REPO_ROOT, env=env,
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, \
            f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n" \
            f"{proc.stderr[-4000:]}"
        assert "BENCH_PASS True" in proc.stdout
        with open(out) as f:
            doc = json.load(f)
        assert doc["ingest"]["within_budget"]
        assert doc["store_stats"]["points_total"] > 0  # push path fed it
        assert doc["query"]["fanin_p99_ms"] > 0
        assert doc["memory"]["within_memory_bound"]

    def test_checked_in_baseline_holds(self):
        path = os.path.join(REPO_ROOT, "BENCH_metrics.json")
        assert os.path.exists(path), "BENCH_metrics.json baseline missing"
        with open(path) as f:
            doc = json.load(f)
        assert doc["pass"] is True
        assert doc["ingest"]["within_budget"]
        assert doc["memory"]["within_memory_bound"]
        # The compare gate actually covers the backplane metrics.
        sys.path.insert(0, REPO_ROOT)
        import bench
        out = bench.compare_bench(path, path, threshold=0.10)
        assert not out["regressions"]
        flat = bench._flatten_bench(doc)
        gated = [p for p in flat
                 if bench._metric_direction(p) is not None]
        assert any("overhead_pct" in p for p in gated)
        assert any("fanin_p99_ms" in p for p in gated)
