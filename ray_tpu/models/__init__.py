"""Model zoo: pure-jax pytree models with logical-axis sharding annotations."""

from .llama import (LlamaConfig, init_params, forward, loss_fn,
                    param_logical_axes, llama_tiny, llama_125m, llama_1b,
                    llama_7b)
from .mlp import MLPConfig, init_mlp, mlp_forward, mlp_loss

__all__ = [
    "LlamaConfig", "init_params", "forward", "loss_fn", "param_logical_axes",
    "llama_tiny", "llama_125m", "llama_1b", "llama_7b",
    "MLPConfig", "init_mlp", "mlp_forward", "mlp_loss",
]
