"""Numerics tests for the ops layer on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops import (apply_rope, attention, flash_attention, moe_layer,
                         reference_attention, ring_attention,
                         rms_norm, rope_frequencies, top_k_routing)
from ray_tpu.ops.ring_attention import ring_attention_sharded
from ray_tpu.ops.ulysses import ulysses_attention_sharded
from ray_tpu.parallel import MeshSpec, build_mesh


def test_devices_available():
    assert len(jax.devices()) == 8


class TestRmsNorm:
    def test_matches_manual(self):
        x = jax.random.normal(jax.random.key(0), (4, 16), jnp.float32)
        w = jnp.ones(16) * 1.5
        out = rms_norm(x, w)
        expect = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * 1.5
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_bf16_io(self):
        x = jax.random.normal(jax.random.key(1), (4, 16)).astype(jnp.bfloat16)
        assert rms_norm(x, jnp.ones(16)).dtype == jnp.bfloat16


class TestRope:
    def test_norm_preserved(self):
        cos, sin = rope_frequencies(32, 128)
        x = jax.random.normal(jax.random.key(0), (2, 4, 64, 32))
        out = apply_rope(x, cos, sin)
        np.testing.assert_allclose(np.linalg.norm(out, axis=-1),
                                   np.linalg.norm(x, axis=-1), rtol=1e-4)

    def test_position_zero_identity(self):
        cos, sin = rope_frequencies(16, 8)
        x = jax.random.normal(jax.random.key(0), (1, 1, 1, 16))
        np.testing.assert_allclose(apply_rope(x, cos, sin), x, rtol=1e-5)

    def test_explicit_positions_match_implicit(self):
        cos, sin = rope_frequencies(16, 64)
        x = jax.random.normal(jax.random.key(0), (1, 2, 10, 16))
        pos = jnp.arange(10)
        np.testing.assert_allclose(apply_rope(x, cos, sin, positions=pos),
                                   apply_rope(x, cos, sin), rtol=1e-5)


def _qkv(key, B=2, H=4, Hkv=None, S=128, D=32, dtype=jnp.float32):
    Hkv = Hkv or H
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (B, H, S, D), dtype),
            jax.random.normal(ks[1], (B, Hkv, S, D), dtype),
            jax.random.normal(ks[2], (B, Hkv, S, D), dtype))


class TestFlashAttention:
    def test_matches_reference_causal(self):
        q, k, v = _qkv(jax.random.key(0))
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64,
                              interpret=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def test_matches_reference_noncausal(self):
        q, k, v = _qkv(jax.random.key(1), S=64)
        ref = reference_attention(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, block_q=32,
                              interpret=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def test_gqa(self):
        q, k, v = _qkv(jax.random.key(2), H=8, Hkv=2, S=64)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=32,
                              interpret=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def test_dispatcher_cpu_fallback(self):
        q, k, v = _qkv(jax.random.key(3), S=32)
        out = attention(q, k, v)  # on CPU -> reference path
        np.testing.assert_allclose(out, reference_attention(q, k, v),
                                   atol=1e-6)

    def test_multi_k_block_online_softmax(self):
        # block_k < Sk exercises the m/l/acc carry across K blocks.
        q, k, v = _qkv(jax.random.key(4), S=128)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=64,
                              interpret=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    @pytest.mark.parametrize("causal", [True, False])
    def test_backward_matches_reference(self, causal):
        q, k, v = _qkv(jax.random.key(5), S=128)
        do = jax.random.normal(jax.random.key(6), q.shape)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) * do)

        ref_fn = loss(lambda q, k, v: reference_attention(
            q, k, v, causal=causal))
        fl_fn = loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=64, interpret=True))
        gr = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gr, gf, ("dq", "dk", "dv")):
            np.testing.assert_allclose(b, a, atol=5e-4, rtol=1e-3,
                                       err_msg=name)

    def test_backward_gqa_offset(self):
        # GQA group-sum of dk/dv plus a ring-style q_offset.
        B, H, Hkv, Sq, Sk, D = 1, 4, 2, 64, 128, 32
        ks = jax.random.split(jax.random.key(7), 4)
        q = jax.random.normal(ks[0], (B, H, Sq, D))
        k = jax.random.normal(ks[1], (B, Hkv, Sk, D))
        v = jax.random.normal(ks[2], (B, Hkv, Sk, D))
        do = jax.random.normal(ks[3], (B, H, Sq, D))

        gr = jax.grad(lambda q, k, v: jnp.sum(reference_attention(
            q, k, v, causal=True, q_offset=64) * do), argnums=(0, 1, 2))(
                q, k, v)
        gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=32, block_k=64, q_offset=64,
            interpret=True) * do), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gr, gf, ("dq", "dk", "dv")):
            np.testing.assert_allclose(b, a, atol=5e-4, rtol=1e-3,
                                       err_msg=name)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        mesh = build_mesh(MeshSpec(sp=8))
        q, k, v = _qkv(jax.random.key(0), B=1, H=4, S=256, D=16)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_gqa(self):
        mesh = build_mesh(MeshSpec(sp=4, dp=2))
        q, k, v = _qkv(jax.random.key(1), B=2, H=8, Hkv=2, S=128, D=16)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)


class TestUlysses:
    def test_matches_reference(self):
        mesh = build_mesh(MeshSpec(sp=8))
        q, k, v = _qkv(jax.random.key(0), B=1, H=8, S=128, D=16)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)


class TestMoE:
    def test_routing_topk(self):
        x = jax.random.normal(jax.random.key(0), (2, 8, 16))
        rw = jax.random.normal(jax.random.key(1), (16, 4))
        info = top_k_routing(x, rw, k=2)
        nz = (np.asarray(info.combine_weights) > 0).sum(-1)
        assert (nz == 2).all()
        np.testing.assert_allclose(
            np.asarray(info.combine_weights).sum(-1), 1.0, rtol=1e-5)

    def test_moe_layer_shapes_and_grad(self):
        B, S, E, M, X = 2, 8, 16, 32, 4
        ks = jax.random.split(jax.random.key(0), 5)
        x = jax.random.normal(ks[0], (B, S, E))
        rw = jax.random.normal(ks[1], (E, X)) * 0.1
        wg = jax.random.normal(ks[2], (X, E, M)) * 0.1
        wu = jax.random.normal(ks[3], (X, E, M)) * 0.1
        wd = jax.random.normal(ks[4], (X, M, E)) * 0.1
        out, aux = moe_layer(x, rw, wg, wu, wd, k=2)
        assert out.shape == (B, S, E)
        assert np.isfinite(aux)

        def loss(rw):
            o, a = moe_layer(x, rw, wg, wu, wd, k=2)
            return (o ** 2).mean() + 0.01 * a
        g = jax.grad(loss)(rw)
        assert np.isfinite(np.asarray(g)).all()

    def test_sparse_dispatch_matches_dense_at_full_capacity(self):
        # Capacity >= T means nothing drops: sparse == dense exactly.
        B, S, E, M, X = 2, 8, 16, 32, 4
        ks = jax.random.split(jax.random.key(1), 5)
        x = jax.random.normal(ks[0], (B, S, E))
        rw = jax.random.normal(ks[1], (E, X)) * 0.1
        wg = jax.random.normal(ks[2], (X, E, M)) * 0.1
        wu = jax.random.normal(ks[3], (X, E, M)) * 0.1
        wd = jax.random.normal(ks[4], (X, M, E)) * 0.1
        dense, _ = moe_layer(x, rw, wg, wu, wd, k=2, capacity_factor=0.0)
        # capacity_factor X/k -> capacity == T: no token can overflow.
        sparse, _ = moe_layer(x, rw, wg, wu, wd, k=2,
                              capacity_factor=X / 2)
        np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                                   atol=1e-5, rtol=1e-4)

    def test_sparse_dispatch_capacity_drops_and_grads(self):
        from ray_tpu.ops.moe import capacity_dispatch
        B, S, E, M, X = 2, 16, 16, 32, 4
        ks = jax.random.split(jax.random.key(2), 5)
        x = jax.random.normal(ks[0], (B, S, E))
        rw = jax.random.normal(ks[1], (E, X)) * 0.1
        info = top_k_routing(x, rw, k=2)
        capacity = 4  # far below T*k/X = 16: forces drops
        dispatch, combine = capacity_dispatch(info, X, capacity)
        # No expert slot is double-assigned; per-expert load <= capacity.
        per_slot = np.asarray(dispatch).sum(axis=0)  # [X, C]
        assert (per_slot <= 1.0 + 1e-6).all()
        assert (np.asarray(dispatch).sum(axis=(0, 2)) <= capacity).all()
        # Dropped tokens have zero combine weight but output stays finite
        # and differentiable.
        wg = jax.random.normal(ks[2], (X, E, M)) * 0.1
        wu = jax.random.normal(ks[3], (X, E, M)) * 0.1
        wd = jax.random.normal(ks[4], (X, M, E)) * 0.1

        def loss(rw):
            o, a = moe_layer(x, rw, wg, wu, wd, k=2, capacity_factor=0.5)
            return (o ** 2).mean() + 0.01 * a
        g = jax.grad(loss)(rw)
        assert np.isfinite(np.asarray(g)).all()

    def test_sorted_dispatch_invariants(self):
        from ray_tpu.ops.moe import sorted_dispatch
        B, S, E, X, k = 2, 16, 8, 4, 2
        ks = jax.random.split(jax.random.key(3), 2)
        x = jax.random.normal(ks[0], (B, S, E))
        rw = jax.random.normal(ks[1], (E, X)) * 0.1
        info = top_k_routing(x, rw, k=k)
        capacity = 4  # below T*k/X = 16: forces drops
        tok_s, e_s, slot_s, w_s, keep = sorted_dispatch(info, X, capacity)
        tok_s, e_s, slot_s, keep = (np.asarray(tok_s), np.asarray(e_s),
                                    np.asarray(slot_s), np.asarray(keep))
        # Kept (expert, slot) pairs are unique and within capacity.
        kept = [(int(e), int(s)) for e, s, f in zip(e_s, slot_s, keep) if f]
        assert len(kept) == len(set(kept))
        assert all(0 <= s < capacity for _e, s in kept)
        # Per-expert kept load <= capacity; dropped slots read as OOB.
        for e in range(X):
            assert sum(1 for ee, _s in kept if ee == e) <= capacity
        assert (slot_s[~keep] == capacity).all()
        # Every (token, expert) assignment appears exactly once.
        pairs = sorted(zip(tok_s.tolist(), e_s.tolist()))
        want = sorted((t, int(e))
                      for t in range(B * S)
                      for e in np.asarray(info.expert_index).reshape(
                          B * S, k)[t])
        assert pairs == want


class TestMeshSharding:
    def test_mesh_spec_resolution(self):
        spec = MeshSpec(dp=-1, tp=2).resolved(8)
        assert spec.dp == 4 and spec.tp == 2

    def test_mesh_build_axes(self):
        mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        assert dict(zip(mesh.axis_names, mesh.devices.shape))["dp"] == 2
        assert mesh.devices.size == 8

    def test_logical_to_pspec(self):
        from ray_tpu.parallel import default_rules, logical_to_pspec
        p = logical_to_pspec(("batch", "seq", "embed"), default_rules())
        assert p[0] == ("dp", "fsdp")
        # embed maps to fsdp but fsdp already shards batch -> dropped
        assert p[2] is None

    def test_shard_pytree(self):
        from ray_tpu.parallel import default_rules, shard_pytree
        mesh = build_mesh(MeshSpec(dp=4, tp=2))
        tree = {"w": jnp.zeros((8, 16)), "b": jnp.zeros((16,))}
        logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
        sharded = shard_pytree(tree, logical, mesh)
        assert sharded["w"].sharding.spec[1] == "tp"
