"""Prefill worker: bucketed prompt prefill only, producing KV handoffs.

One half of the disaggregated topology (reference analog: DistServe /
Splitwise prefill instances; the reference's serving stack reaches the
same split through vLLM's prefill-decode disaggregation).  A prefill
worker owns NO paged cache and NO decode slots — it runs the
length-bucketed prefill program, samples the first token, and publishes
the prompt's K/V as a :class:`KVHandoff` for a decode worker to import.
Long prompts therefore never stall a decode batch: they burn compute on
the prefill tier instead.
"""

from __future__ import annotations

import math
import time
from functools import partial
from typing import List, Optional

import numpy as np

from ...util import telemetry
from ..engine import SamplingParams, sample_logits
from .handoff import KVHandoff


class PrefillWorker:
    """Runs prefill-only on its own chips; stateless between requests."""

    def __init__(self, params, cfg, *,
                 prefill_buckets: tuple = (64, 256, 1024),
                 page_size: int = 16, seed: int = 0):
        import jax

        from .. import _model

        self._jax = jax
        self.params = params
        self.cfg = cfg
        self.page_size = page_size
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self._prefills = {
            b: jax.jit(partial(_model.prefill, cfg=cfg))
            for b in self.prefill_buckets}
        self._rng = np.random.default_rng(seed)

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return None

    def prefill(self, prompt_tokens: List[int],
                params: Optional[SamplingParams] = None,
                t_submit: float = 0.0) -> KVHandoff:
        """Prefill one prompt and package the handoff (raises
        ValueError for prompts beyond every bucket — the router rejects
        those at admission, before prefill compute is spent)."""
        import jax.numpy as jnp

        params = params or SamplingParams()
        n = len(prompt_tokens)
        bucket = self._bucket_for(n)
        if bucket is None:
            raise ValueError(
                f"prompt of {n} tokens exceeds the largest prefill "
                f"bucket ({self.prefill_buckets[-1]})")
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = prompt_tokens
        with telemetry.profile_span(
                "engine_prefill", "llm",
                extra={"prompt_len": n, "disagg": True}):
            logits, ks, vs = self._prefills[bucket](
                self.params, jnp.asarray(toks), jnp.asarray(n))
        telemetry.inc("ray_tpu_llm_tokens_total", n,
                      tags={"kind": "prompt"})
        first = sample_logits(np.asarray(logits), params, self._rng)
        # Trim the handoff to the prompt's pages rounded UP to a power
        # of two: transfer bytes stay within 2x the prompt (not the
        # bucket), while the decode side's jitted scatter sees at most
        # log2(pages-per-bucket) distinct shapes instead of one per
        # prompt length (same idiom as the engine's chunk-shape cache).
        need = max(1, math.ceil(n / self.page_size))
        keep = min(bucket, (1 << (need - 1).bit_length()) * self.page_size)
        return KVHandoff(
            prompt_tokens=list(prompt_tokens), first_token=int(first),
            ks=np.asarray(ks[:, :keep]), vs=np.asarray(vs[:, :keep]),
            params=params, t_submit=t_submit,
            t_first=time.perf_counter())
