"""Windowed aggregation over stored points (the query half of the store).

Semantics follow PromQL where it has an opinion:

* ``delta``/``rate`` on counters are **reset-aware**: the increase is
  measured from the *last reset* inside the window (a restarted process
  re-counts from zero; its stale prefix must not produce a negative or
  phantom-huge delta).  On gauges they are the plain signed first-to-
  last difference.
* ``pNN`` reconstructs the window's observation distribution from the
  cumulative-bucket delta between the window's endpoints, then linearly
  interpolates inside the owning bucket (PromQL ``histogram_quantile``).
* The last point *before* the window start serves as the delta baseline
  (like PromQL range vectors extending one sample left), so a 60 s
  window over a 10 s-interval series still sees a full-width delta.

Multi-series combination (a tag filter matching several tag-sets):
counter-like values (``delta``/``rate``/counter ``last``) **sum** across
series — they are cluster totals; everything else takes the mean (or
min/max for those aggs).  ``pNN`` sums the per-series bucket deltas
first and computes one quantile over the merged distribution.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

AGGS = ("rate", "delta", "avg", "min", "max", "last")

_QUANTILE_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)$")


class ScalarPoint(NamedTuple):
    t: float
    v: float


class HistPoint(NamedTuple):
    t: float
    counts: Tuple[float, ...]  # cumulative per-bucket, +Inf last
    sum: float
    count: int


def parse_quantile(agg: str) -> Optional[float]:
    """``"p99"`` -> 0.99, ``"p99.9"`` -> 0.999; None for plain aggs."""
    m = _QUANTILE_RE.match(agg or "")
    if not m:
        return None
    q = float(m.group(1)) / 100.0
    return q if 0.0 < q < 1.0 else None


def validate_agg(agg: str) -> bool:
    return agg in AGGS or parse_quantile(agg) is not None


def _window(points: Sequence, start: float, end: float):
    """(baseline point before start or None, in-window points)."""
    base = None
    win: List = []
    for p in points:
        if p.t < start:
            base = p
        elif p.t <= end:
            win.append(p)
    return base, win


def _hist_quantile(q: float, bounds: Sequence[float],
                   per_bucket: Sequence[float]) -> Optional[float]:
    total = sum(per_bucket)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, n in enumerate(per_bucket):
        if n <= 0:
            continue
        if cum + n >= target:
            if i >= len(bounds):      # +Inf bucket: clamp to last bound
                return float(bounds[-1]) if bounds else None
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * ((target - cum) / n)
        cum += n
    return float(bounds[-1]) if bounds else None


def hist_window_delta(base: Optional[HistPoint], win: Sequence[HistPoint]
                      ) -> Tuple[Tuple[float, ...], float, int]:
    """Window delta with baseline fallback: prefer the last point before
    the window; else the first in-window point (PromQL ``increase``
    loses pre-first-sample counts the same way); a lone point with no
    baseline exports its full cumulative state."""
    eff = base if base is not None else (win[0] if len(win) > 1 else None)
    return _hist_window_delta(eff, win[-1])


def _hist_window_delta(base: Optional[HistPoint], last: HistPoint
                       ) -> Tuple[Tuple[float, ...], float, int]:
    """Cumulative-vector delta (counts, sum, count) across the window; a
    shrunk count means the source process restarted, so the window
    restarts at zero too (the post-restart cumulative IS the delta)."""
    if base is None or not base.counts or \
            len(base.counts) != len(last.counts):
        return last.counts, last.sum, last.count
    if last.count < base.count or \
            any(l < b for l, b in zip(last.counts, base.counts)):
        return last.counts, last.sum, last.count
    return (tuple(l - b for l, b in zip(last.counts, base.counts)),
            last.sum - base.sum, last.count - base.count)


def _scalar_delta(seq: List[ScalarPoint], counter: bool
                  ) -> Tuple[Optional[float], Optional[float]]:
    """(delta, span_s) over the point sequence; counter deltas measure
    from the last reset (value drop) so a restart yields 0, not a
    negative."""
    if len(seq) < 2:
        return None, None
    first = seq[0]
    if counter:
        for i in range(len(seq) - 1, 0, -1):
            if seq[i].v < seq[i - 1].v:
                first = seq[i]
                break
    span = seq[-1].t - first.t
    return seq[-1].v - first.v, span


def aggregate_window(points: Sequence, mtype: str,
                     bounds: Optional[Sequence[float]],
                     start: float, end: float, agg: str
                     ) -> Tuple[Optional[float], int, Optional[Tuple]]:
    """One series' windowed aggregate: ``(value, points_in_window,
    hist_delta)`` — ``hist_delta`` is ``(bounds, per_bucket)`` for
    quantile aggs so the caller can merge distributions across series
    before taking the quantile."""
    base, win = _window(points, start, end)
    if not win:
        return None, 0, None
    n = len(win)
    q = parse_quantile(agg)

    if mtype == "histogram":
        last = win[-1]
        dcounts, dsum, dcount = hist_window_delta(base, win)
        # Cumulative-in-le -> per-bucket counts for the window.
        per = [max(0.0, dcounts[i] - (dcounts[i - 1] if i else 0.0))
               for i in range(len(dcounts))]
        if q is not None:
            return (_hist_quantile(q, bounds or (), per), n,
                    (tuple(bounds or ()), tuple(per)))
        if agg == "delta":
            return float(dcount), n, None
        if agg == "rate":
            span = last.t - (base.t if base is not None else win[0].t)
            return (dcount / span if span > 0 else None), n, None
        if agg == "avg":
            return (dsum / dcount if dcount > 0 else None), n, None
        if agg == "last":
            return (last.sum / last.count if last.count else None), n, None
        return None, n, None  # min/max undefined on histograms

    values = [p.v for p in win]
    if q is not None:
        return None, n, None  # pNN needs a histogram series
    if agg == "last":
        return values[-1], n, None
    if agg == "avg":
        return sum(values) / len(values), n, None
    if agg == "min":
        return min(values), n, None
    if agg == "max":
        return max(values), n, None
    if agg in ("delta", "rate"):
        seq = ([base] if base is not None else []) + list(win)
        delta, span = _scalar_delta(seq, counter=(mtype == "counter"))
        if agg == "delta":
            return delta, n, None
        return (delta / span if delta is not None and span and span > 0
                else None), n, None
    return None, n, None


def combine_results(per_series: List[Tuple[Optional[float], int,
                                           Optional[Tuple]]],
                    agg: str, mtype: str) -> Tuple[Optional[float], int]:
    """Fold per-series windowed results into one value (see module doc
    for the sum-vs-mean rules)."""
    n = sum(r[1] for r in per_series)
    q = parse_quantile(agg)
    if q is not None:
        merged: Dict[Tuple, List[float]] = {}
        for _v, _n, hist in per_series:
            if not hist:
                continue
            bounds, per = hist
            acc = merged.setdefault(bounds, [0.0] * len(per))
            if len(acc) == len(per):
                for i, c in enumerate(per):
                    acc[i] += c
        if not merged:
            return None, n
        # Differing boundary sets can't merge; take the worst quantile.
        vals = [_hist_quantile(q, b, per) for b, per in merged.items()]
        vals = [v for v in vals if v is not None]
        return (max(vals) if vals else None), n
    values = [r[0] for r in per_series if r[0] is not None]
    if not values:
        return None, n
    summable = (mtype == "counter" and agg in ("delta", "rate", "last")) \
        or (mtype == "histogram" and agg in ("delta", "rate"))
    if summable:
        return sum(values), n
    if agg == "min":
        return min(values), n
    if agg == "max":
        return max(values), n
    return sum(values) / len(values), n


def history_points(points: Sequence, mtype: str, start: float, end: float,
                   max_points: int) -> List[List[Optional[float]]]:
    """Sparkline rows ``[age_s, value]`` (oldest first).  Histograms
    render the inter-point incremental average — the per-interval mean
    latency — so a spike shows as a spike, not as a drift of the
    lifetime mean."""
    _base, win = _window(points, start, end)
    rows: List[List[Optional[float]]] = []
    if mtype == "histogram":
        prev = _base
        for p in win:
            if prev is not None and p.count >= prev.count and \
                    len(prev.counts) == len(p.counts):
                dc, ds = p.count - prev.count, p.sum - prev.sum
            else:
                dc, ds = p.count, p.sum
            rows.append([round(end - p.t, 3),
                         (ds / dc) if dc > 0 else None])
            prev = p
    else:
        rows = [[round(end - p.t, 3), p.v] for p in win]
    if len(rows) > max_points:
        stride = -(-len(rows) // max_points)
        tail = rows[-1]
        rows = rows[::stride]
        if rows[-1] is not tail:
            rows.append(tail)
    return rows
