"""ray_tpu.llm.disagg — disaggregated LLM serving.

Prefill/decode split with KV handoff through the shm object store,
SLO-aware admission control (per-class token budgets, bounded queues
with deadline shedding, KV-occupancy backpressure), and the open-loop
``serve_load`` bench harness.  Reference analog: the vLLM-backed
serving stack the reference wraps (python/ray/llm/_internal/serve/)
and the DistServe/Splitwise prefill-decode disaggregation pattern it
deploys in production.
"""

from .handoff import KVHandoff, export_handoff, import_handoff
from .loadgen import ServeLoadSpec, run_open_loop
from .prefill import PrefillWorker
from .router import (AdmissionConfig, AdmissionController, DisaggServer,
                     OverloadError, RequestClass, build_disagg_deployment)

__all__ = [
    "KVHandoff", "export_handoff", "import_handoff",
    "PrefillWorker",
    "AdmissionConfig", "AdmissionController", "RequestClass",
    "DisaggServer", "OverloadError", "build_disagg_deployment",
    "ServeLoadSpec", "run_open_loop",
]
