"""LLM engine tests: paged-cache decode correctness vs the full forward,
continuous batching, page accounting, serve integration (reference analog:
python/ray/llm tests — the reference delegates correctness to vLLM; here
the engine is ours so exactness is asserted against the training model)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.llm import InferenceEngine, SamplingParams
from ray_tpu.models import LlamaConfig
from ray_tpu.models.llama import forward, init_params

CFG = LlamaConfig(vocab_size=128, hidden=32, layers=2, heads=4, kv_heads=2,
                  head_dim=8, mlp_dim=64, max_seq_len=128,
                  dtype=jnp.float32, attention_impl="reference", remat=False)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def naive_greedy(params, prompt, max_new):
    """Gold: full forward re-run per token."""
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = forward(params, jnp.asarray([toks]), CFG)
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    return out


class TestInferenceEngine:
    def test_greedy_matches_full_forward(self, params):
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16, 64))
        prompt = [3, 17, 92, 5, 41]
        got = eng.generate([prompt], SamplingParams(max_tokens=8))[0]
        want = naive_greedy(params, prompt, 8)
        assert got == want

    def test_chunked_decode_matches_per_step(self, params):
        """Device-resident multi-token chunks (step_chunk: lax.scan with
        on-device sampling, one host sync per chunk) produce exactly the
        per-token greedy stream."""
        prompts = [[3, 17, 92, 5, 41], [7, 9, 23, 6]]
        sp = SamplingParams(max_tokens=9)
        eng_a = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                                num_pages=64, prefill_buckets=(16,))
        ids = [eng_a.add_request(p, sp) for p in prompts]
        done = {}
        guard = 0
        while eng_a.has_work():
            for r in eng_a.step_chunk(4):
                done[r.request_id] = r.output_tokens
            guard += 1
            assert guard < 100
        chunked = [done[i] for i in ids]
        want = [naive_greedy(params, p, 9) for p in prompts]
        assert chunked == want

    def test_pipelined_decode_matches_per_step(self, params):
        """Double-buffered chunk pipelining (run_pipelined: host applies
        chunk k while the device runs k+1) is a pure latency
        optimization — the greedy token streams are identical, including
        mid-flight admission at a pipeline bubble."""
        prompts = [[3, 17, 92, 5, 41], [7, 9, 23, 6], [11, 4], [8, 8, 2]]
        sp = SamplingParams(max_tokens=9)
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16,))
        # max_slots=2 < 4 prompts forces admission waves mid-pipeline.
        ids = [eng.add_request(p, sp) for p in prompts]
        done = {r.request_id: r.output_tokens
                for r in eng.run_pipelined(4, max_chunks=200)}
        got = [done[i] for i in ids]
        want = [naive_greedy(params, p, 9) for p in prompts]
        assert got == want

    def test_continuous_batching_matches_sequential(self, params):
        prompts = [[7, 9, 23], [4, 4, 8, 15, 16, 23, 42], [99], [1, 2]]
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16, 64))
        # max_slots=2 < 4 prompts forces admission waves mid-decode.
        batch = eng.generate(prompts, SamplingParams(max_tokens=6))
        for p, got in zip(prompts, batch):
            assert got == naive_greedy(params, p, 6)

    def test_pages_freed_after_generation(self, params):
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=32, prefill_buckets=(16,))
        free0 = eng.pool.num_free
        eng.generate([[5, 6, 7]] * 3, SamplingParams(max_tokens=4))
        assert eng.pool.num_free == free0

    def test_kv_memory_backpressure(self, params):
        # Tiny pool: requests must queue on page exhaustion yet all finish.
        eng = InferenceEngine(params, CFG, max_slots=4, page_size=8,
                              num_pages=8, prefill_buckets=(16,))
        outs = eng.generate([[i + 1, i + 2] for i in range(5)],
                            SamplingParams(max_tokens=4))
        assert all(len(o) == 4 for o in outs)

    def test_too_long_prompt_rejected(self, params):
        eng = InferenceEngine(params, CFG, max_slots=2, page_size=8,
                              num_pages=64, prefill_buckets=(16,),
                              max_seq_len=32)
        outs = eng.generate([list(range(1, 40)), [5, 6]],
                            SamplingParams(max_tokens=4))
        assert outs[0] == []          # rejected: prompt_too_long
        assert len(outs[1]) == 4

    def test_stop_tokens(self, params):
        eng = InferenceEngine(params, CFG, max_slots=1, page_size=8,
                              num_pages=64, prefill_buckets=(16,))
        prompt = [3, 17, 92, 5, 41]
        full = naive_greedy(params, prompt, 8)
        stop = full[2]
        got = eng.generate([prompt], SamplingParams(
            max_tokens=8, stop_token_ids=(stop,)))[0]
        assert got == full[:3]        # stops when the stop token appears


class TestLLMServing:
    def test_serve_deployment_end_to_end(self, ray_start):
        import ray_tpu
        from ray_tpu import serve
        from ray_tpu.llm import build_llm_deployment

        def build():
            return init_params(CFG, jax.random.key(0)), CFG

        app = build_llm_deployment(build, name="tiny_llm",
                                   engine_options={
                                       "max_slots": 2, "page_size": 8,
                                       "num_pages": 64,
                                       "prefill_buckets": (16,)})
        h = serve.run(app)
        prompt = [3, 17, 92, 5, 41]
        out = ray_tpu.get(h.remote({"prompt_tokens": prompt,
                                    "max_tokens": 6}), timeout=120)
        expected = naive_greedy(init_params(CFG, jax.random.key(0)),
                                prompt, 6)
        assert out["output_tokens"] == expected
        assert out["finish_reason"] == "length"
        # Token streaming: the stream method yields the same tokens one by
        # one through a streaming actor call (num_returns="streaming").
        gen = h.options(stream=True, method_name="stream").remote(
            {"prompt_tokens": prompt, "max_tokens": 6})
        items = [ray_tpu.get(r, timeout=120) for r in gen]
        streamed = [it["token"] for it in items if "token" in it]
        assert streamed == expected
        assert items[-1]["finish_reason"] == "length"
        serve.shutdown()
