"""C++ task/actor gateway: a schema'd TCP protocol native clients speak.

The reference's C++ user API (`cpp/src/ray/api.cc`) rides the protobuf
core-worker ABI; this framework's internal wire is pickled dataclasses,
which non-Python clients cannot (and must not) speak.  The gateway is the
bridge: a documented, fixed-schema JSON-over-TCP protocol
(``cpp/include/ray_tpu/client.hpp`` is the header-only C++ client) that
exposes task submission, actor method calls, and object gets to native
code — large tensors hand off zero-copy through the typed shm segments of
``util/cpp_io.py`` instead of JSON.

Frames: 4-byte little-endian length + UTF-8 JSON object.  First frame
must be {"op": "auth", "token": "<hex>"}.  Then:

  {"op": "submit", "fn": <registered name>, "args": [...]}
      -> {"ok": true, "ref": "<hex>"}
  {"op": "call_actor", "actor": <name>, "namespace": <ns|null>,
   "method": <name>, "args": [...]}
      -> {"ok": true, "ref": "<hex>"}
  {"op": "get", "ref": "<hex>", "timeout": <seconds>}
      -> {"ok": true, "result": <json>}                       (plain)
      -> {"ok": true, "tensor_segment": "<shm name>"}         (ndarray
         results: map with cpp/include/ray_tpu/tensor_writer.hpp layout)
  {"op": "ping"} -> {"ok": true}

Functions and actors are explicitly registered server-side
(``register_function`` / ``export_actor``) — the gateway never unpickles
or eval's anything a native client sends, so a client can only invoke
what the owner exported (reference analog: the function-descriptor
allowlists of cross-language calls).  Exported actor handles are resolved
once and cached; a restart-proof client re-exports.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional

import ray_tpu

_registry: Dict[str, Any] = {}


def register_function(name: str, fn: Callable) -> None:
    """Export ``fn`` to native clients under ``name``.  The RemoteFunction
    wrapper is built once here so per-submit calls reuse the pickled
    function blob (fn_id caching downstream)."""
    _registry[name] = ray_tpu.remote(fn)


# (actor name, namespace) -> (allowed method names | None=all public,
# cached handle).  Mirrors register_function: native clients can only
# drive actors the owner exported, and the handle resolves once instead
# of a get_actor round-trip per call.
_actor_exports: Dict[tuple, list] = {}


def export_actor(name: str, namespace: Optional[str] = None,
                 methods: Optional[list] = None) -> None:
    """Export the named actor to native clients.  ``methods`` restricts
    the callable surface; None allows every public (non-underscore)
    method."""
    _actor_exports[(name, namespace)] = [
        None if methods is None else list(methods), None]


class CppGateway:
    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 token: Optional[str] = None):
        self.token = token or os.urandom(12).hex()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address = self._sock.getsockname()
        self._closed = False
        # hex -> ObjectRef, insertion-ordered and bounded: fire-and-forget
        # clients must not pin results forever — beyond the cap the oldest
        # unfetched ref drops (normal GC frees the object).
        from collections import OrderedDict
        self._refs: "OrderedDict[str, Any]" = OrderedDict()
        self._refs_cap = 10_000
        self._refs_lock = threading.Lock()
        # Tensor hand-off segments whose replies may never be consumed
        # (client crash): unlinked at stop() unless the client already did.
        self._segments: set = set()
        from ._private import sanitizer
        sanitizer.spawn(self._accept_loop, name="cpp-gateway")

    # -- framing ----------------------------------------------------------- #

    @staticmethod
    def _recv_frame(conn) -> Optional[dict]:
        hdr = b""
        while len(hdr) < 4:
            chunk = conn.recv(4 - len(hdr))
            if not chunk:
                return None
            hdr += chunk
        (n,) = struct.unpack("<I", hdr)
        if n > 64 << 20:
            return None
        body = b""
        while len(body) < n:
            chunk = conn.recv(min(1 << 16, n - len(body)))
            if not chunk:
                return None
            body += chunk
        try:
            return json.loads(body)
        except ValueError:
            return None

    @staticmethod
    def _send_frame(conn, obj: dict) -> None:
        body = json.dumps(obj).encode()
        conn.sendall(struct.pack("<I", len(body)) + body)

    # -- serving ----------------------------------------------------------- #

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            from ._private import sanitizer
            sanitizer.spawn(self._serve, args=(conn,),
                            name="cpp-gateway-serve")

    def _serve(self, conn) -> None:
        try:
            hello = self._recv_frame(conn)
            if not hello or hello.get("op") != "auth" or \
                    hello.get("token") != self.token:
                self._send_frame(conn, {"ok": False, "error": "auth"})
                return
            self._send_frame(conn, {"ok": True})
            while True:
                msg = self._recv_frame(conn)
                if msg is None:
                    return
                try:
                    self._send_frame(conn, self._handle(msg))
                except Exception as e:  # noqa: BLE001
                    self._send_frame(conn, {"ok": False,
                                            "error": repr(e)})
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _track(self, ref) -> str:
        hexid = ref.hex()
        with self._refs_lock:
            self._refs[hexid] = ref
            while len(self._refs) > self._refs_cap:
                self._refs.popitem(last=False)
        return hexid

    def _handle(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True}
        if op == "submit":
            remote = _registry.get(msg.get("fn", ""))
            if remote is None:
                return {"ok": False,
                        "error": f"unknown function {msg.get('fn')!r}"}
            ref = remote.remote(*msg.get("args", []))
            return {"ok": True, "ref": self._track(ref)}
        if op == "call_actor":
            key = (msg["actor"], msg.get("namespace"))
            export = _actor_exports.get(key)
            if export is None:
                return {"ok": False,
                        "error": f"actor {key[0]!r} not exported"}
            mname = msg["method"]
            allowed = export[0]
            if mname.startswith("_") or \
                    (allowed is not None and mname not in allowed):
                return {"ok": False,
                        "error": f"method {mname!r} not exported"}
            if export[1] is None:
                export[1] = ray_tpu.get_actor(key[0], namespace=key[1])
            # Submission never fails synchronously here — a stale handle
            # (actor re-created under the name) surfaces as ActorError at
            # get, which invalidates the cache (see the get op below).
            ref = getattr(export[1], mname).remote(*msg.get("args", []))
            return {"ok": True, "ref": self._track(ref)}
        if op == "get":
            hexid = msg.get("ref", "")
            with self._refs_lock:
                ref = self._refs.get(hexid)
            if ref is None:
                return {"ok": False, "error": f"unknown ref {hexid!r}"}
            try:
                value = ray_tpu.get(ref, timeout=msg.get("timeout", 300))
            except Exception as e:
                from ray_tpu._private.exceptions import ActorError
                if isinstance(e, ActorError):
                    # The target may have been re-created under its name:
                    # drop cached handles so the next call re-resolves.
                    for exp in _actor_exports.values():
                        exp[1] = None
                raise
            with self._refs_lock:
                self._refs.pop(hexid, None)
            import numpy as np
            if isinstance(value, np.ndarray):
                from ray_tpu.util import cpp_io
                seg = f"/rtgw_{os.getpid()}_{os.urandom(4).hex()}"
                cpp_io.export_tensors(seg, [value])
                self._segments.add(seg)
                return {"ok": True, "tensor_segment": seg}
            return {"ok": True, "result": value}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def stop(self) -> None:
        self._closed = True
        # A thread blocked in accept() does not observe close() on Linux
        # (it keeps blocking on the old fd): wake it with a dummy
        # connect first, the same treatment node.py gives its acceptor —
        # otherwise the gateway thread outlives stop() (sanitizer
        # finding).
        try:
            s = socket.create_connection(self.address, timeout=1.0)
            s.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except Exception:
            pass
        # Sweep hand-off segments whose clients never consumed/unlinked
        # them (the consumer owns cleanup in the happy path).
        from multiprocessing import shared_memory
        for seg in list(self._segments):
            try:
                sm = shared_memory.SharedMemory(name=seg.lstrip("/"))
                sm.close()
                sm.unlink()
            except FileNotFoundError:
                pass
            except Exception:
                pass
            self._segments.discard(seg)


def start(port: int = 0, host: str = "127.0.0.1",
          token: Optional[str] = None) -> CppGateway:
    """Start the native-client gateway; returns the server (``.address``,
    ``.token`` go to the C++ side, e.g. via argv or env)."""
    return CppGateway(port=port, host=host, token=token)
