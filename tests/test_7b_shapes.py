"""North-star shape verification: the Llama-2-7B training step
(BASELINE.json config) AOT-lowers and compiles on a virtual 8-device mesh
with fsdp=8 and a pp=2 variant — no weights materialized, nothing
executed.  Proves the multi-chip 7B sharding is compile-clean before
hardware exists (reference: BASELINE.json Llama-2-7B SFT north star)."""

import json
import os
import subprocess
import sys


def test_llama2_7b_aot_compiles():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--spec", "7b"],
        capture_output=True, text=True, timeout=1500, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.startswith("{")]
    names = {d["metric"]: d for d in lines}
    assert "llama2_7b_fsdp8_aot_compile" in names
    assert "llama2_7b_pp2_fsdp4_aot_compile" in names
    for name, d in names.items():
        if d.get("skipped"):
            # Legacy jax (< 0.6, no jax.shard_map) cannot lower the
            # GPipe island's partial-auto shard_map on XLA-CPU; the
            # bench reports the pp spec skipped-with-reason there.
            assert name == "llama2_7b_pp2_fsdp4_aot_compile", name
            assert "shard_map" in d["skipped"]
            continue
        assert d["ok"] and d["params_b"] > 6.0
