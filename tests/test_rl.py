"""RL library tests: envs, rollouts, buffers, GAE, PPO and DQN learning.

Reference analogs: rllib per-algorithm tests (rllib/algorithms/ppo/tests/,
dqn/tests/) and rllib/core/learner tests, scaled to CI-size workloads.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (CartPole, DQNConfig, EnvRunner, EnvRunnerGroup,
                        PPOConfig, PrioritizedReplayBuffer, ReplayBuffer,
                        StatelessGuess, VectorEnv, compute_gae)


class TestEnvs:
    def test_cartpole_dynamics(self):
        env = CartPole()
        obs, _ = env.reset(seed=0)
        assert obs.shape == (4,)
        total = 0.0
        for _ in range(50):
            obs, r, term, trunc, _ = env.step(1)
            total += r
            if term or trunc:
                break
        assert total >= 1.0

    def test_vector_env_autoreset(self):
        vec = VectorEnv(CartPole, 3, seed=0)
        obs = vec.reset()
        assert obs.shape == (3, 4)
        # Drive with constant action until some env resets.
        saw_done = False
        for _ in range(200):
            obs, rewards, dones, terms, final_obs = vec.step(
                np.ones(3, np.int32))
            assert obs.shape == (3, 4)
            if dones.any():
                saw_done = True
                i = int(np.nonzero(dones)[0][0])
                # pre-reset state is out of bounds; post-reset is near 0
                assert not np.allclose(final_obs[i], obs[i])
                break
        assert saw_done


class TestEnvRunner:
    def test_sample_shapes(self, ray_start):
        runner = EnvRunner(CartPole, num_envs=2, seed=0)
        batch = runner.sample(16)
        assert batch["obs"].shape == (16, 2, 4)
        assert batch["actions"].shape == (16, 2)
        assert batch["last_values"].shape == (2,)
        m = runner.metrics()
        assert "episode_return_mean" in m

    def test_remote_group_sync(self, ray_start):
        group = EnvRunnerGroup(CartPole, num_env_runners=2,
                               num_envs_per_runner=2)
        try:
            rollouts = group.sample(8)
            assert len(rollouts) == 2
            assert rollouts[0]["obs"].shape == (8, 2, 4)
            runner = EnvRunner(CartPole, num_envs=1, seed=123)
            group.sync_weights(runner.params)
        finally:
            group.stop()


class TestBuffers:
    def test_replay_ring(self):
        buf = ReplayBuffer(8, seed=0)
        buf.add(x=np.arange(6, dtype=np.float32))
        assert len(buf) == 6
        buf.add(x=np.arange(6, 12, dtype=np.float32))
        assert len(buf) == 8  # wrapped
        s = buf.sample(4)
        assert s["x"].shape == (4,)

    def test_prioritized(self):
        buf = PrioritizedReplayBuffer(16, seed=0)
        buf.add(x=np.arange(10, dtype=np.float32))
        batch, idx, w = buf.sample(5)
        assert w.shape == (5,) and w.max() <= 1.0
        buf.update_priorities(idx, np.full(5, 10.0))
        # High-priority items dominate subsequent sampling.
        batch2, idx2, _ = buf.sample(200)
        frac = np.isin(idx2, idx).mean()
        assert frac > 0.5


class TestGAE:
    def test_terminal_vs_truncation(self):
        rewards = np.ones((3, 1), np.float32)
        values = np.zeros((3, 1), np.float32)
        dones = np.array([[False], [False], [True]])
        last = np.zeros(1, np.float32)
        # terminated at t=2: no bootstrap
        terms = dones.copy()
        adv_t, ret_t = compute_gae(rewards, values, dones, terms, last,
                                   0.99, 1.0)
        # truncated at t=2: bootstrap from V(final_obs)=100 recorded at t=2
        boot = np.zeros((3, 1), np.float32)
        boot[2, 0] = 100.0
        adv_u, ret_u = compute_gae(rewards, values, dones,
                                   np.zeros_like(terms), last, 0.99, 1.0,
                                   boot)
        assert ret_u[2, 0] == pytest.approx(1 + 0.99 * 100.0, rel=1e-5)
        assert ret_t[2, 0] == pytest.approx(1.0, rel=1e-5)
        assert ret_t[0, 0] == pytest.approx(1 + 0.99 + 0.99 ** 2, rel=1e-4)

    def test_no_bootstrap_from_reset_state(self):
        # After a truncation the next buffer row is the new episode's reset
        # state; GAE must not credit it to the old episode.
        rewards = np.ones((2, 1), np.float32)
        values = np.array([[0.0], [55.0]], np.float32)  # V(reset)=55
        dones = np.array([[True], [False]])
        terms = np.zeros_like(dones)
        boot = np.zeros((2, 1), np.float32)  # trunc bootstrap value = 0
        adv, ret = compute_gae(rewards, values, dones, terms,
                               np.zeros(1, np.float32), 0.99, 1.0, boot)
        assert ret[0, 0] == pytest.approx(1.0, rel=1e-5)  # not 1+0.99*55


class TestPPO:
    def test_learns_stateless_guess(self, ray_start):
        algo = (PPOConfig()
                .environment(lambda: StatelessGuess(4))
                .env_runners(num_envs_per_env_runner=8,
                             rollout_fragment_length=64)
                .training(lr=5e-3, num_epochs=4, minibatch_size=128,
                          entropy_coeff=0.0)
                .debugging(seed=0)
                .build_algo())
        try:
            first = algo.train()
            last = None
            for _ in range(14):
                last = algo.train()
            ret = last["env_runners"]["episode_return_mean"]
            # Random play ~= 0.25; learned policy should beat it clearly.
            assert ret > 0.6, f"PPO failed to learn: return={ret}"
            assert last["learner"]["loss"] == last["learner"]["loss"]  # not NaN
        finally:
            algo.stop()

    def test_checkpoint_roundtrip(self, ray_start, tmp_path):
        algo = (PPOConfig().environment("CartPole-v1")
                .env_runners(rollout_fragment_length=8)
                .build_algo())
        try:
            algo.train()
            ckpt = algo.save(str(tmp_path / "ckpt"))
            w0 = algo.get_weights()
            algo2 = (PPOConfig().environment("CartPole-v1")
                     .env_runners(rollout_fragment_length=8)
                     .build_algo())
            algo2.restore(ckpt)
            import jax
            for a, b in zip(jax.tree.leaves(w0),
                            jax.tree.leaves(algo2.get_weights())):
                np.testing.assert_allclose(a, b)
            assert algo2.iteration == algo.iteration
            algo2.stop()
        finally:
            algo.stop()

    def test_multi_learner_ddp(self, ray_start):
        algo = (PPOConfig()
                .environment(lambda: StatelessGuess(2))
                .env_runners(num_envs_per_env_runner=4,
                             rollout_fragment_length=16)
                .learners(num_learners=2)
                .training(minibatch_size=64)
                .build_algo())
        try:
            lg = algo.learner_group
            # Gradient sync is an allreduce among the learner actors, not a
            # driver tree-mean (reference: DDP across learner workers).
            assert lg._ddp, "learner collective group failed to form"
            res = algo.train()
            assert np.isfinite(res["learner"]["loss"])
            # DDP contract: replicas stay bit-identical after updates even
            # though the driver never touched a gradient.
            import jax
            import ray_tpu
            w = [ray_tpu.get(r.get_weights.remote()) for r in lg.remotes]
            for a, b in zip(jax.tree.leaves(w[0]), jax.tree.leaves(w[1])):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        finally:
            algo.stop()


class TestDQN:
    def test_learns_stateless_guess(self, ray_start):
        algo = (DQNConfig()
                .environment(lambda: StatelessGuess(2))
                .env_runners(rollout_fragment_length=256)
                .training(lr=5e-3, learning_starts=64, buffer_size=4096,
                          target_update_freq=128, epsilon_decay_steps=1024,
                          train_batch_size=32)
                .debugging(seed=0)
                .build_algo())
        try:
            last = None
            for _ in range(8):
                last = algo.train()
            ret = last["env_runners"]["episode_return_mean"]
            assert ret > 0.7, f"DQN failed to learn: return={ret}"
            assert last["epsilon"] < 0.2
            assert last["buffer_size"] > 0
        finally:
            algo.stop()

    def test_prioritized_replay_path(self, ray_start):
        algo = (DQNConfig()
                .environment(lambda: StatelessGuess(2))
                .env_runners(rollout_fragment_length=128)
                .training(learning_starts=32, prioritized_replay=True,
                          train_batch_size=16)
                .build_algo())
        try:
            res = algo.train()
            assert np.isfinite(res["learner"].get("loss", 0.0))
        finally:
            algo.stop()
