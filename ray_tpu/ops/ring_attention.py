"""Ring attention: context parallelism over the ICI ring.

Absent from the reference (SURVEY §2.4 SP/CP row: `grep -ri ring_attention`
over the reference returns nothing) — built natively for TPU.  The sequence
is sharded over the ``sp`` mesh axis; K/V blocks rotate around the ring via
``jax.lax.ppermute`` (one ICI hop per step) while each device accumulates
attention for its resident Q block with the flash-style online softmax
(running max + denominator), so the full [seq, seq] score matrix never
exists anywhere and per-device memory is O(seq/sp).

Compute/communication overlap: each ppermute transfers the next K/V block
while the current block's two matmuls run on the MXU — XLA schedules the
collective-permute asynchronously (start/done) around the dots.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Attention over a sequence sharded on ``axis_name``.

    Must be called inside shard_map/pjit with ``axis_name`` bound.
    q/k/v: [B, H|Hkv, S_local, D] (local sequence shard, seq-contiguous
    layout: device i holds positions [i*S_local, (i+1)*S_local)).
    """
    B, H, Sl, D = q.shape
    _, Hkv, _, _ = k.shape
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale_ = scale if scale is not None else 1.0 / math.sqrt(D)
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    q32 = q.astype(jnp.float32)
    qpos = my_idx * Sl + jnp.arange(Sl)

    def step(s, carry):
        m, l, acc, kc, vc = carry
        src = (my_idx - s) % n  # which block we currently hold
        kpos = src * Sl + jnp.arange(Sl)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, kc.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale_
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        m_blk = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        # Guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1.
        safe = m_new > NEG_INF / 2
        corr = jnp.where(safe, jnp.exp(m - m_new), 1.0)
        e = jnp.where(safe, jnp.exp(scores - m_new), 0.0)
        l_new = l * corr + jnp.sum(e, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", e, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        # Rotate K/V one hop around the ring: i -> i+1.
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, kc, vc

    m0 = jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, Sl, D), jnp.float32)
    m, l, acc, _, _ = jax.lax.fori_loop(
        0, n, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh=None, *, axis_name: str = "sp",
                           causal: bool = True,
                           scale: Optional[float] = None,
                           in_spec=None):
    """Convenience wrapper: shard_map ring_attention over ``axis_name``.

    Arrays are [B, H, S, D] with S sharded over axis_name.  ``in_spec``
    overrides the full PartitionSpec when batch/head dims are also sharded
    (as inside a GSPMD forward: batch on (dp,fsdp), heads on tp); mesh=None
    uses the installed global mesh.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        from ..parallel.mesh import get_global_mesh
        mesh = get_global_mesh()
    spec = in_spec if in_spec is not None else P(None, None, axis_name, None)
    fn = partial(ring_attention, axis_name=axis_name, causal=causal,
                 scale=scale)
    if hasattr(jax, "shard_map"):
        wrapped = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                                out_specs=spec, check_vma=False)
    else:  # pre-stable API (jax < 0.6)
        from jax.experimental.shard_map import shard_map as _shard_map
        wrapped = _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_rep=False)
    return wrapped(q, k, v)
