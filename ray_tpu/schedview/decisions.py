"""Bounded scheduler decision ring (the control-plane flight recorder).

Reference analog: the GCS task-event buffer keeps task STATE transitions
(src/ray/gcs/gcs_task_manager.h:97); nothing in the reference keeps the
scheduler's DECISIONS — the autoscaler reconstructs demand from resource
shapes instead.  Here every ``_try_place``/``_hybrid_pick``/PG-commit
outcome lands in one bounded ring on the head, so "why is this pending"
and "why node X" are point lookups, not log archaeology.

Hot-path contract: recording is ONE ``deque.append`` of a tuple plus an
integer bump — no locks, no dict churn, no string formatting.  Folding
tuples into the per-task "latest decision" index happens lazily at read
time (same batching idiom as ``_private/events.py``), and everything
stringy (scheduling-class reprs, node hex) is produced at snapshot time.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

# -- rejection reason codes (closed vocabulary) -----------------------------
#
# Every rejected placement is tallied under one of these; `ray-tpu task
# why`, state.explain_task() and the bench's saturation-phase assertions
# all match on them, so additions here must ride a README update.
R_INSUFFICIENT = "insufficient_resources"  # node alive but lacks capacity NOW
R_DRAINING = "draining"                    # drain fence excluded the node
R_AFFINITY = "affinity_miss"               # hard NodeAffinity target unusable
R_BUNDLE = "bundle_unavailable"            # PG bundle not committed / full
R_INFEASIBLE = "infeasible"                # no node could EVER satisfy it
R_PENDING_DEPS = "pending_deps"            # upstream ObjectIDs unresolved
R_NO_NODES = "no_nodes"                    # empty cluster

REASON_CODES = (R_INSUFFICIENT, R_DRAINING, R_AFFINITY, R_BUNDLE,
                R_INFEASIBLE, R_PENDING_DEPS, R_NO_NODES)

# Decision kinds (what produced the record).
K_INLINE = "inline"        # submit-time fast-path placement
K_LOOP = "loop"            # scheduler-loop placement
K_EXCHANGE = "exchange"    # lease reuse (finished task's booking handed on)
K_PIPELINE = "pipeline"    # queued ahead on a busy worker (no booking)
K_REJECT = "reject"        # a ready class failed to place this round
K_INFEASIBLE = "infeasible"  # parked: no node could ever satisfy it
K_PG_COMMIT = "pg_commit"  # placement-group two-phase commit succeeded
K_PG_REJECT = "pg_reject"  # placement-group prepare found no assignment

# -- global enable switch ---------------------------------------------------

_enabled = os.environ.get("RAY_TPU_SCHED_TRACE", "1").strip().lower() \
    not in ("0", "false", "no", "off")


def enabled() -> bool:
    """Whether scheduler decision tracing is on (module-global: one
    read on the submit path)."""
    return _enabled


def set_enabled(value: bool) -> None:
    """Toggle decision tracing (the control_plane bench's off/on
    overhead reps; operators use RAY_TPU_SCHED_TRACE=0)."""
    global _enabled
    _enabled = bool(value)


def _class_str(key: Any) -> str:
    """Human-readable scheduling-class key (resources + strategy); the
    raw key holds ID objects, so stringification is snapshot-time only.
    ``res`` may be an items-tuple (the scheduler's class key) or a
    ResourceSet (hot-path success records skip the sorted-key build)."""
    if isinstance(key, str):  # PG records carry the strategy name
        return key
    try:
        res, pg, bundle, strat = key
        if hasattr(res, "to_dict"):
            res = res.to_dict().items()
        parts = [",".join(f"{k}:{v:g}" for k, v in res) or "no-resources"]
        if pg is not None:
            parts.append(f"pg={pg.hex()[:8]}b{bundle}")
        if strat is not None:
            if isinstance(strat, tuple) and strat and strat[0] == "affinity":
                parts.append(f"affinity={strat[1].hex()[:8]}"
                             f"{'~' if strat[2] else ''}")
            else:
                parts.append(str(strat))
        return " ".join(parts)
    except Exception:  # noqa: BLE001 — display-only
        return repr(key)


class DecisionRing:
    """Bounded, lazily-folded ring of scheduler decision records.

    ``push`` is on the per-decision hot path; it appends a raw tuple
    ``(mono, wall, kind, task_id_hex, name, class_key, candidates,
    rejected, node_hex, attempt)`` and bumps a plain int counter.  The
    per-task latest-decision index (what ``explain`` reads) is built at
    fold time under the ring lock.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = max(64, int(capacity))
        self._pending: deque = deque()
        self._records: deque = deque()
        self._latest: "OrderedDict[str, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        self.num_dropped = 0
        # Plain-int per-kind totals (flushed into the telemetry counter
        # by the scheduler's rate-limited publisher, never on hot path).
        self.counts: Dict[str, int] = {}
        self._fold_at = max(256, self.capacity // 2)

    # -- hot path -----------------------------------------------------------

    def push(self, kind: str, task_id_hex: Optional[str], name: str,
             class_key: Any, candidates: int,
             rejected: Optional[Dict[str, int]], node_hex: Optional[str],
             attempt: int) -> None:
        # One clock read per decision: records carry the monotonic stamp
        # only, and snapshot() maps mono->wall through a single offset
        # computed at read time.
        # Documented lock-free hot path: deque.append is thread-safe and
        # _fold() drains under the lock; counts is written only by the
        # scheduler loop (single writer) and read advisorily.
        self._pending.append((time.monotonic(), kind,  # ray-tpu: noqa[RT401]
                              task_id_hex, name, class_key, candidates,
                              rejected, node_hex, attempt))
        self.counts[kind] = self.counts.get(kind, 0) + 1  # ray-tpu: noqa[RT401]
        if len(self._pending) >= self._fold_at:
            self._fold()

    # -- folding / reads ----------------------------------------------------

    def _fold(self) -> None:
        with self._lock:
            while True:
                try:
                    rec = self._pending.popleft()
                except IndexError:
                    break
                self._records.append(rec)
                if len(self._records) > self.capacity:
                    self._records.popleft()
                    self.num_dropped += 1
                tid = rec[2]
                if tid is not None:
                    self._latest[tid] = rec
                    self._latest.move_to_end(tid)
                    if len(self._latest) > self.capacity:
                        self._latest.popitem(last=False)

    @staticmethod
    def _to_dict(rec: tuple,
                 wall_offset: Optional[float] = None) -> Dict[str, Any]:
        (mono, kind, tid, name, key, candidates, rejected, node,
         attempt) = rec
        if wall_offset is None:
            # Not an interval: the one-off mono->wall basis shift for
            # display (records carry only the monotonic stamp).
            wall_offset = time.time() - time.monotonic()  # ray-tpu: noqa[RT203]
        return {
            "time": mono + wall_offset, "mono": mono, "kind": kind,
            "task_id": tid,
            "name": name, "sched_class": _class_str(key),
            "candidates": candidates, "rejected": dict(rejected or {}),
            "node_id": node, "attempt": attempt,
        }

    def snapshot(self, task_id: Optional[str] = None,
                 limit: int = 200) -> List[Dict[str, Any]]:
        """Newest-last decision records; ``task_id`` filters (prefix ok:
        operators paste truncated ids)."""
        self._fold()
        out: List[Dict[str, Any]] = []
        # Mono->wall basis shift for display, not an interval.
        wall_offset = time.time() - time.monotonic()  # ray-tpu: noqa[RT203]
        with self._lock:
            records = list(self._records)
        for rec in reversed(records):
            if task_id is not None and \
                    not (rec[2] or "").startswith(task_id):
                continue
            out.append(self._to_dict(rec, wall_offset))
            if len(out) >= limit:
                break
        out.reverse()
        return out

    def latest_for(self, task_id: str) -> Optional[Dict[str, Any]]:
        """The newest decision recorded for one task (exact id)."""
        self._fold()
        with self._lock:
            rec = self._latest.get(task_id)
        return self._to_dict(rec) if rec is not None else None

    def rate(self, window_s: float = 5.0) -> float:
        """Decisions/s over the trailing window (bounded by ring
        capacity — a saturated ring under-reports, which num_dropped
        makes visible)."""
        self._fold()
        cutoff = time.monotonic() - window_s
        with self._lock:
            n = sum(1 for rec in reversed(self._records)
                    if rec[0] >= cutoff)
        return n / window_s if window_s > 0 else 0.0

    def stats(self) -> Dict[str, Any]:
        self._fold()
        with self._lock:
            size = len(self._records)
        # Advisory snapshot: slightly-stale counters are fine for stats.
        return {"counts": dict(self.counts),
                "total": sum(self.counts.values()),
                "size": size, "capacity": self.capacity,
                "num_dropped": self.num_dropped}  # ray-tpu: noqa[RT401]

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._records.clear()
            self._latest.clear()
            self.counts = {}
            self.num_dropped = 0
