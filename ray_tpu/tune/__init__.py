"""ray_tpu.tune — hyperparameter search (Ray Tune equivalent).

Reference analog: Tuner.fit (reference: python/ray/tune/tuner.py:43,319) ->
TuneController (tune/execution/tune_controller.py:68) managing Trainable
actors; search spaces (tune/search/), schedulers (tune/schedulers/ — ASHA,
median-stopping).  Here trials are runtime tasks; intermediate reports and
early-stop signals flow through the KV store.
"""

from .search import choice, grid_search, loguniform, randint, uniform
from .searchers import (BasicVariantSearcher, ConcurrencyLimiter, Repeater,
                        Searcher, TPESearcher)
from .tuner import (ResultGrid, TrialResult, TuneConfig, Tuner,
                    get_checkpoint, report, TuneStopException)
from .schedulers import (ASHAScheduler, FIFOScheduler, HyperBandScheduler,
                         MedianStoppingRule, PopulationBasedTraining)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TrialResult", "report",
    "get_checkpoint", "TuneStopException",
    "grid_search", "choice", "uniform", "loguniform", "randint",
    "Searcher", "BasicVariantSearcher", "TPESearcher",
    "ConcurrencyLimiter", "Repeater",
    "ASHAScheduler", "FIFOScheduler", "MedianStoppingRule",
    "HyperBandScheduler", "PopulationBasedTraining",
]
