"""Data library tests (reference pattern: python/ray/data/tests — local
ray.init + operator unit tests)."""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data import (BlockAccessor, Dataset, from_items, from_numpy,
                          range as ds_range)


class TestDatasetBasics:
    def test_range_count(self, ray_start):
        assert ds_range(100, parallelism=4).count() == 100

    def test_from_items_take(self, ray_start):
        ds = from_items([{"a": i} for i in range(10)], parallelism=3)
        assert [r["a"] for r in ds.take(5)] == [0, 1, 2, 3, 4]

    def test_map_batches(self, ray_start):
        def double(batch):
            return {"id": batch["id"] * 2}
        out = ds_range(10, parallelism=2).map_batches(double).take_all()
        assert sorted(r["id"] for r in out) == [2 * i for i in range(10)]

    def test_map_and_filter(self, ray_start):
        ds = (ds_range(20, parallelism=2)
              .map(lambda r: {"id": r["id"], "sq": int(r["id"]) ** 2})
              .filter(lambda r: r["sq"] % 2 == 0))
        rows = ds.take_all()
        assert all(r["sq"] == r["id"] ** 2 for r in rows)
        assert all(r["sq"] % 2 == 0 for r in rows)

    def test_fused_stage_chain(self, ray_start):
        ds = (ds_range(12, parallelism=3)
              .map_batches(lambda b: {"id": b["id"] + 1})
              .map_batches(lambda b: {"id": b["id"] * 10}))
        assert sorted(r["id"] for r in ds.take_all()) == \
            [10 * (i + 1) for i in range(12)]

    def test_flat_map(self, ray_start):
        ds = from_items([1, 2], parallelism=1).flat_map(
            lambda r: [{"v": r["item"]}, {"v": r["item"] * 100}])
        assert sorted(r["v"] for r in ds.take_all()) == [1, 2, 100, 200]

    def test_repartition_and_shuffle(self, ray_start):
        ds = ds_range(100, parallelism=2).repartition(5).materialize()
        assert ds.num_blocks() == 5
        shuffled = ds_range(100, parallelism=2).random_shuffle(seed=0)
        ids = [r["id"] for r in shuffled.take_all()]
        assert sorted(ids) == list(range(100))
        assert ids != list(range(100))

    def test_schema(self, ray_start):
        s = from_numpy({"x": np.zeros((5, 3), np.float32)}).schema()
        assert s["x"] == "float32"

    def test_split(self, ray_start):
        shards = ds_range(90, parallelism=4).split(3)
        counts = [s.count() for s in shards]
        assert counts == [30, 30, 30]
        all_ids = sorted(r["id"] for s in shards for r in s.take_all())
        assert all_ids == list(range(90))


class TestIterBatches:
    def test_exact_batches(self, ray_start):
        batches = list(ds_range(64, parallelism=4).iter_batches(
            batch_size=16))
        assert len(batches) == 4
        assert all(len(b["id"]) == 16 for b in batches)

    def test_remainder(self, ray_start):
        batches = list(ds_range(70, parallelism=4).iter_batches(
            batch_size=16))
        assert sum(len(b["id"]) for b in batches) == 70
        batches = list(ds_range(70, parallelism=4).iter_batches(
            batch_size=16, drop_last=True))
        assert all(len(b["id"]) == 16 for b in batches)

    def test_device_put_iterator(self, ray_start):
        import jax
        from ray_tpu.data import device_put_iterator
        it = ds_range(32, parallelism=2).iter_batches(batch_size=16)
        dev_batches = list(device_put_iterator(it))
        assert len(dev_batches) == 2
        assert all(isinstance(b["id"], jax.Array) for b in dev_batches)


class TestIO:
    def test_parquet_roundtrip(self, ray_start):
        import pyarrow as pa
        import pyarrow.parquet as pq
        with tempfile.TemporaryDirectory() as tmp:
            for i in range(3):
                pq.write_table(
                    pa.table({"x": list(np.arange(i * 10, (i + 1) * 10))}),
                    os.path.join(tmp, f"part{i}.parquet"))
            ds = Dataset.read_parquet(os.path.join(tmp, "*.parquet"))
            assert ds.count() == 30
            out = ds.map_batches(lambda b: {"x": b["x"] * 2}).take_all()
            assert sorted(r["x"] for r in out) == [2 * i for i in range(30)]

    def test_csv(self, ray_start):
        with tempfile.TemporaryDirectory() as tmp:
            p = os.path.join(tmp, "t.csv")
            with open(p, "w") as f:
                f.write("a,b\n1,x\n2,y\n")
            rows = Dataset.read_csv(p).take_all()
            assert [int(r["a"]) for r in rows] == [1, 2]


from ray_tpu import data


class TestDistributedShuffle:
    """Two-stage task shuffle (reference: _internal/planner/exchange/) +
    streaming execution."""

    def test_shuffle_runs_as_tasks_not_driver(self, ray_start):
        ds = data.range(4000, parallelism=8).random_shuffle(seed=7)
        from ray_tpu.data.executor import execute
        out = execute(ds)
        # Outputs are refs produced by reduce tasks: the driver never held
        # the concatenated data.
        assert all(isinstance(b, ray_tpu.ObjectRef) for b in out)
        rows = sorted(r["id"] for r in ds.take_all())
        assert rows == list(range(4000))

    def test_shuffle_changes_order_deterministically(self, ray_start):
        a = data.range(1000, parallelism=4).random_shuffle(seed=3).take_all()
        b = data.range(1000, parallelism=4).random_shuffle(seed=3).take_all()
        c = data.range(1000, parallelism=4).random_shuffle(seed=4).take_all()
        ids = lambda rows: [r["id"] for r in rows]  # noqa: E731
        assert ids(a) == ids(b)
        assert ids(a) != ids(c)
        assert ids(a) != list(range(1000))

    def test_repartition_distributed(self, ray_start):
        ds = data.range(999, parallelism=3).repartition(5)
        blocks = ds.materialize()
        assert blocks.num_blocks() == 5
        assert blocks.count() == 999

    def test_iter_batches_overlaps_produce_consume(self, ray_start):
        import time as _t

        def slow(block):
            _t.sleep(0.4)
            return block

        # Warm the worker pool so timings measure pipeline overlap, not
        # process spin-up.
        data.range(8, parallelism=8).map_batches(lambda b: b).take_all()

        ds = data.range(800, parallelism=8).map_batches(slow)
        t0 = _t.monotonic()
        it = ds.iter_batches(batch_size=100)
        first = next(it)
        t_first = _t.monotonic() - t0
        rest = list(it)
        t_all = _t.monotonic() - t0
        assert len(first["id"]) == 100
        # First batch arrives well before the full pipeline drains.
        assert t_first < t_all * 0.8, (t_first, t_all)
        # And within ~2x one task's duration (+CPU-steal headroom for the
        # 1-core CI box): iter_batches yields the first *completed* block
        # (preserve_order=False default), so one slow/late task cannot
        # head-of-line-block the consumer.
        assert t_first < 2 * 0.4 + 0.8, (t_first, t_all)

    def test_shuffle_after_map_fuses(self, ray_start):
        ds = (data.range(500, parallelism=4)
              .map_batches(lambda b: {"id": b["id"] * 2})
              .random_shuffle(seed=1))
        rows = sorted(r["id"] for r in ds.take_all())
        assert rows == [2 * i for i in range(500)]


class TestSortGroupby:
    """Distributed sort + groupby/aggregate (reference test analog:
    python/ray/data/tests/test_sort.py, test_all_to_all.py groupby)."""

    def test_sort_ascending_descending(self, ray_start):
        import numpy as np
        rng = np.random.default_rng(0)
        vals = rng.permutation(500).astype(np.int64)
        ds = from_numpy({"x": vals}, parallelism=6).sort("x")
        out = np.concatenate(
            [b["x"] for b in ds._blocks()
             if b and len(b.get("x", [])) > 0])
        np.testing.assert_array_equal(out, np.arange(500))
        ds2 = from_numpy({"x": vals}, parallelism=6).sort(
            "x", descending=True)
        out2 = np.concatenate(
            [b["x"] for b in ds2._blocks() if b and len(b.get("x", []))])
        np.testing.assert_array_equal(out2, np.arange(499, -1, -1))

    def test_sort_after_map_fuses_into_exchange(self, ray_start):
        import numpy as np
        ds = (ds_range(100, parallelism=4)
              .map_batches(lambda b: {"x": 99 - b["id"]})
              .sort("x"))
        out = np.concatenate([b["x"] for b in ds._blocks()
                              if b and len(b.get("x", []))])
        np.testing.assert_array_equal(out, np.arange(100))

    def test_groupby_aggregates(self, ray_start):
        import numpy as np
        n = 300
        ds = from_numpy({
            "k": np.arange(n) % 7,
            "v": np.arange(n, dtype=np.float64),
        }, parallelism=5)
        res = ds.groupby("k").aggregate(
            total=("v", "sum"), n=("v", "count"), avg=("v", "mean"),
            lo=("v", "min"), hi=("v", "max")).take_all()
        assert len(res) == 7
        by_key = {int(r["k"]): r for r in res}
        for k in _builtins_range(7):
            vals = np.arange(n)[np.arange(n) % 7 == k].astype(float)
            assert by_key[k]["total"] == pytest.approx(vals.sum())
            assert by_key[k]["n"] == len(vals)
            assert by_key[k]["avg"] == pytest.approx(vals.mean())
            assert by_key[k]["lo"] == vals.min()
            assert by_key[k]["hi"] == vals.max()

    def test_groupby_convenience_and_map_groups(self, ray_start):
        import numpy as np
        ds = from_items(
            [{"k": "a", "v": 1.0}, {"k": "b", "v": 2.0},
             {"k": "a", "v": 3.0}, {"k": "b", "v": 4.0},
             {"k": "c", "v": 5.0}], parallelism=3)
        counts = {r["k"]: r["count"] for r in ds.groupby("k").count()
                  .take_all()}
        assert counts == {"a": 2, "b": 2, "c": 1}
        means = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v")
                 .take_all()}
        assert means["a"] == pytest.approx(2.0)
        # map_groups: normalize within each group.
        normed = ds.groupby("k").map_groups(
            lambda b: {"k": b["k"], "v": b["v"] - b["v"].mean()}).take_all()
        got = sorted((r["k"], round(float(r["v"]), 3)) for r in normed)
        assert got == [("a", -1.0), ("a", 1.0), ("b", -1.0), ("b", 1.0),
                       ("c", 0.0)]

    def test_column_ops_and_unique(self, ray_start):
        import numpy as np
        ds = from_numpy({"a": np.arange(20), "b": np.arange(20) % 4,
                         "c": np.ones(20)}, parallelism=3)
        sel = ds.select_columns(["a", "b"]).take(1)[0]
        assert set(sel) == {"a", "b"}
        dropped = ds.drop_columns(["c"]).take(1)[0]
        assert set(dropped) == {"a", "b"}
        renamed = ds.rename_columns({"a": "x"}).take(1)[0]
        assert set(renamed) == {"x", "b", "c"}
        assert ds.unique("b") == [0, 1, 2, 3]
        # Renaming onto an existing column is data loss: reject it.
        with pytest.raises(Exception, match="duplicate target"):
            ds.rename_columns({"a": "b"}).take(1)

    def test_limit_and_union(self, ray_start):
        a = ds_range(50, parallelism=4)
        b = ds_range(10, parallelism=2)
        lim = a.limit(7)
        assert [r["id"] for r in lim.take_all()] == list(_builtins_range(7))
        u = a.union(b)
        assert u.count() == 60

    def test_writes_roundtrip(self, ray_start, tmp_path):
        import numpy as np
        ds = from_numpy({"x": np.arange(40),
                            "y": np.arange(40) * 2.0}, parallelism=3)
        pq_dir = str(tmp_path / "pq")
        files = ds.write_parquet(pq_dir)
        assert len(files) == 3
        back = Dataset.read_parquet(pq_dir + "/*.parquet")
        assert sorted(r["x"] for r in back.take_all()) == list(
            _builtins_range(40))
        csv_dir = str(tmp_path / "csv")
        ds.write_csv(csv_dir)
        back_csv = Dataset.read_csv(csv_dir + "/*.csv")
        assert back_csv.count() == 40
        json_dir = str(tmp_path / "js")
        ds.write_json(json_dir)
        import json as _json
        rows = []
        import glob as _glob
        for f in _glob.glob(json_dir + "/*.json"):
            with open(f) as fh:
                rows += [_json.loads(line) for line in fh if line.strip()]
        assert len(rows) == 40


import builtins as _bi
_builtins_range = _bi.range


class TestDatasources:
    """Binary / image / TFRecord readers (reference test analogs:
    python/ray/data/tests/test_image.py, test_tfrecords.py,
    test_binary.py)."""

    def test_read_binary_files(self, ray_start, tmp_path):
        for i in range(5):
            (tmp_path / f"f{i}.bin").write_bytes(bytes([i]) * (i + 1))
        ds = data.read_binary_files(str(tmp_path))
        rows = ds.take_all()
        assert len(rows) == 5
        sizes = sorted(len(r["bytes"]) for r in rows)
        assert sizes == [1, 2, 3, 4, 5]

    def test_read_images_map_iter_streams(self, ray_start, tmp_path):
        from PIL import Image
        import numpy as _np
        for i in range(8):
            arr = _np.full((12, 10, 3), i * 10, _np.uint8)
            Image.fromarray(arr).save(tmp_path / f"img{i}.png")
        (tmp_path / "notes.txt").write_text("ignored")

        ds = (data.read_images(str(tmp_path), size=(6, 5), mode="RGB")
              .map_batches(lambda b: {"image": b["image"].astype(
                  _np.float32) / 255.0, "path": b["path"]}))
        n = 0
        seen_means = []
        for batch in ds.iter_batches(batch_size=4):
            assert batch["image"].shape[1:] == (6, 5, 3)
            assert batch["image"].dtype == _np.float32
            n += len(batch["image"])
            seen_means.extend(batch["image"].mean(axis=(1, 2, 3)).tolist())
        assert n == 8
        assert max(seen_means) <= 1.0

    def test_tfrecord_roundtrip(self, ray_start, tmp_path):
        import numpy as _np
        cols = {
            "idx": _np.arange(50, dtype=_np.int64),
            "score": _np.linspace(0, 1, 50).astype(_np.float32),
            "name": _np.asarray([f"row-{i}" for i in range(50)], object),
        }
        out = str(tmp_path / "records")
        data.from_numpy(cols, parallelism=3).write_tfrecord(out)
        import glob as g
        files = g.glob(out + "/*.tfrecord")
        assert len(files) >= 1

        back = data.read_tfrecord(out, verify_crc=True)
        rows = back.take_all()
        assert len(rows) == 50
        by_idx = sorted(rows, key=lambda r: int(r["idx"]))
        assert int(by_idx[0]["idx"]) == 0 and int(by_idx[-1]["idx"]) == 49
        assert abs(float(by_idx[-1]["score"]) - 1.0) < 1e-6
        assert bytes(by_idx[7]["name"]).decode() == "row-7"

    def test_tfrecord_example_codec(self):
        from ray_tpu.data.datasource import decode_example, encode_example
        import numpy as _np
        payload = encode_example({
            "a": _np.asarray([1, -2, 3], _np.int64),
            "b": _np.asarray([0.5, 1.5], _np.float32),
            "c": b"blob", "d": "text",
        })
        out = decode_example(payload)
        _np.testing.assert_array_equal(out["a"], [1, -2, 3])
        _np.testing.assert_allclose(out["b"], [0.5, 1.5])
        assert out["c"] == [b"blob"] and out["d"] == [b"text"]

    def test_crc32c_known_vectors(self):
        from ray_tpu.data.datasource import crc32c
        # RFC 3720 test vectors.
        assert crc32c(b"") == 0
        assert crc32c(b"\x00" * 32) == 0x8A9136AA
        assert crc32c(b"\xff" * 32) == 0x62A8AB43
        assert crc32c(bytes(_builtin_range(32))) == 0x46DD794E


def _builtin_range(n):
    import builtins
    return builtins.range(n)


class TestBackpressure:
    def test_window_adapts_to_block_size(self):
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.executor import _OpBackpressure

        ctx = DataContext.get()
        bp = _OpBackpressure()
        assert bp.window() == ctx.initial_in_flight
        # Huge blocks: window shrinks to the floor.
        bp._ema = float(ctx.op_memory_budget_bytes)
        assert bp.window() == ctx.min_in_flight
        # Tiny blocks: window grows to the cap.
        bp._ema = 1024.0
        assert bp.window() == ctx.max_in_flight

    def test_streaming_in_flight_bounded_by_budget(self, ray_start):
        """read -> map -> iter with per-op backpressure: once a block's
        size is observed (~2 MiB vs a 4 MiB budget), at most 2 tasks are
        in flight even though 16 blocks and 4 CPUs are available.
        (Store bytes are no proxy here: consumed blocks stay pinned until
        their zero-copy views are GC'd.)"""
        import threading
        import time as _t

        import numpy as _np
        from ray_tpu._private.runtime import driver_runtime
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get()
        old = (ctx.op_memory_budget_bytes, ctx.initial_in_flight)
        ctx.op_memory_budget_bytes = 4 << 20  # 4 MiB budget
        ctx.initial_in_flight = 2
        try:
            def big_block(b):
                n = len(b["id"])
                return {"payload": _np.ones((n, 64 * 1024), _np.float64),
                        "id": b["id"]}  # ~2 MiB per block

            ds = data.range(64, parallelism=16).map_batches(big_block)
            rt = driver_runtime()
            peak = [0]
            stop = [False]

            def sampler():
                while not stop[0]:
                    with rt._running_lock:
                        peak[0] = max(peak[0], len(rt._running))
                    _t.sleep(0.002)

            t = threading.Thread(target=sampler, daemon=True)
            t.start()
            n = 0
            for batch in ds.iter_batches(batch_size=4):
                n += len(batch["id"])
                _t.sleep(0.01)  # slow consumer: backpressure must hold
            stop[0] = True
            t.join(timeout=5)
            assert n == 64
            # initial window 2; after the first observation the window is
            # budget/ema = 2.  Allow +1 for the submit/complete race.
            assert peak[0] <= 3, f"max in-flight tasks {peak[0]}"
        finally:
            (ctx.op_memory_budget_bytes, ctx.initial_in_flight) = old


class TestArrowInterop:
    """Arrow at the edges (reference: ray.data from_arrow/to_arrow_refs,
    arrow_block.py) — blocks stay numpy dicts (the device-feed format),
    Arrow converts zero-copy at the boundary."""

    def test_from_arrow_roundtrip(self, ray_start):
        import numpy as np
        import pyarrow as pa
        t = pa.table({"a": np.arange(100), "b": np.arange(100) * 2.0})
        ds = data.from_arrow(t, parallelism=4)
        rows = ds.take_all()
        assert len(rows) == 100
        assert rows[3] == {"a": 3, "b": 6.0}
        tables = [pa.table({"x": [1, 2]}), pa.table({"x": [3]})]
        ds2 = data.from_arrow(tables)
        assert sorted(r["x"] for r in ds2.take_all()) == [1, 2, 3]

    def test_to_arrow_refs_through_tasks(self, ray_start):
        import pyarrow as pa
        ds = data.range(50, parallelism=5).map_batches(
            lambda b: {"id": b["id"] + 1})
        refs = ds.to_arrow_refs()
        tables = ray_tpu.get(refs, timeout=120)
        assert all(isinstance(t, pa.Table) for t in tables)
        ids = sorted(i for t in tables for i in t.column("id").to_pylist())
        assert ids == list(range(1, 51))

    def test_iter_batches_formats(self, ray_start):
        import pyarrow as pa
        ds = data.range(40, parallelism=2)
        arrow_batches = list(ds.iter_batches(batch_size=10,
                                             batch_format="pyarrow"))
        assert all(isinstance(b, pa.Table) for b in arrow_batches)
        assert sum(b.num_rows for b in arrow_batches) == 40
        pdf = next(iter(ds.iter_batches(batch_size=10,
                                        batch_format="pandas")))
        assert list(pdf.columns) == ["id"] and len(pdf) == 10


class TestArrowBlocks:
    """block_format="arrow": pyarrow Tables as the physical block layout
    (reference: _internal/arrow_block.py) — parquet scans stay zero-copy
    through slice/batch, with numpy materialized only at the consumer
    boundary."""

    @pytest.fixture()
    def arrow_ctx(self):
        from ray_tpu.data.context import DataContext
        ctx = DataContext.get()
        old = ctx.block_format
        ctx.block_format = "arrow"
        yield ctx
        ctx.block_format = old

    def test_parquet_roundtrip_zero_copy(self, ray_start, arrow_ctx,
                                         tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ray_tpu import data as rd

        t = pa.table({"x": np.arange(1000, dtype=np.int64),
                      "y": np.arange(1000, dtype=np.float64) * 0.5})
        pq.write_table(t, str(tmp_path / "a.parquet"))
        ds = rd.read_parquet(str(tmp_path / "a.parquet"))
        blocks = [b for b in ds.iter_batches(batch_size=300,
                                             batch_format="pyarrow")]
        assert all(isinstance(b, pa.Table) for b in blocks)
        assert sum(b.num_rows for b in blocks) == 1000
        # Zero-copy property (checked driver-locally, where buffer
        # identity survives): batch slices of a Table-block dataset
        # share the SOURCE table's buffers — same address, no copies.
        local = rd.from_arrow(t)
        batches = list(local.iter_batches(batch_size=300,
                                          batch_format="pyarrow"))
        src_addr = t.column("x").chunks[0].buffers()[1].address
        for b in batches:
            assert b.column("x").chunks[0].buffers()[1].address \
                == src_addr

    def test_numpy_only_at_consumer_boundary(self, ray_start, arrow_ctx,
                                             tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ray_tpu import data as rd

        pq.write_table(pa.table({"x": np.arange(64, dtype=np.int32)}),
                       str(tmp_path / "b.parquet"))
        ds = rd.read_parquet(str(tmp_path / "b.parquet"))
        batches = list(ds.iter_batches(batch_size=16))
        assert all(isinstance(b, dict) for b in batches)
        assert all(isinstance(v, np.ndarray)
                   for b in batches for v in b.values())
        total = np.concatenate([b["x"] for b in batches])
        assert sorted(total.tolist()) == list(range(64))

    def test_transforms_on_arrow_blocks(self, ray_start, arrow_ctx,
                                        tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ray_tpu import data as rd

        pq.write_table(pa.table({"k": np.repeat([0, 1], 50),
                                 "v": np.arange(100, dtype=np.float64)}),
                       str(tmp_path / "c.parquet"))
        ds = rd.read_parquet(str(tmp_path / "c.parquet"))
        doubled = ds.map_batches(lambda b: {"k": b["k"], "v": b["v"] * 2})
        agg = doubled.groupby("k").mean("v").take_all()
        by_k = {int(r["k"]): r["mean(v)"] for r in agg}
        assert by_k[0] == pytest.approx(np.arange(50).mean() * 2)
        assert by_k[1] == pytest.approx(np.arange(50, 100).mean() * 2)

    def test_arrow_blocks_survive_remote_execution(self, ray_start,
                                                   arrow_ctx, tmp_path):
        """The driver's block_format must reach spawned READ tasks
        (workers have a fresh default DataContext): blocks flowing into
        stages must really be pyarrow Tables, not silently numpy."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ray_tpu import data as rd

        pq.write_table(pa.table({"x": np.arange(128, dtype=np.int64)}),
                       str(tmp_path / "d.parquet"))
        ds = rd.read_parquet(str(tmp_path / "d.parquet"))
        seen = ds.map_batches(
            lambda b: {"mod": np.array([type(b).__module__])},
            batch_format="block").take_all()
        assert all(r["mod"].startswith("pyarrow") for r in seen), seen

    def test_column_ops_on_arrow_blocks(self, ray_start, arrow_ctx,
                                        tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ray_tpu import data as rd

        pq.write_table(pa.table({"x": np.arange(10, dtype=np.int64),
                                 "y": np.ones(10)}),
                       str(tmp_path / "e.parquet"))
        ds = rd.read_parquet(str(tmp_path / "e.parquet"))
        rows = ds.add_column("z", lambda b: b["x"] * 3) \
                 .rename_columns({"y": "w"}) \
                 .drop_columns(["w"]) \
                 .select_columns(["x", "z"]).take_all()
        assert rows[3] == {"x": 3, "z": 9}
        assert ds.unique("x") == list(range(10))
