"""Background checkpoint writer: serialize + write off the train step path.

The contract with the caller (``manager.WorkerCheckpointClient``): the only
blocking work in a save is snapshotting device arrays to host numpy and, if
the bounded in-flight queue is full, waiting for a slot (backpressure — a
saver that outruns the disk must not buffer unbounded host copies).
Everything else — building the shard blob, the tmp+rename publish, the
replica push, the coordinator ack — happens on this thread while the next
train steps run.

Failure semantics: a failed write marks the job failed and NEVER acks, so
the coordinator never commits a manifest over it; the error surfaces on the
next ``raise_on_error()`` / ``close()`` so the train loop notices instead of
silently training past unlanded checkpoints.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..util import telemetry
from . import format as ckpt_format

#: Test hook: sleep this many seconds before each shard write (lets chaos
#: tests kill a worker reliably mid-async-save).
_WRITE_DELAY_ENV = "RAY_TPU_CKPT_TEST_WRITE_DELAY_S"


@dataclass
class WriteJob:
    dirpath: str
    step: int
    rank: int
    world: int
    snapshot: ckpt_format.Snapshot
    #: Called on the writer thread after a successful publish with
    #: (job, index, blob, write_seconds); acks/replica pushes live here.
    on_done: Optional[Callable] = None
    enqueued_mono: float = field(default_factory=time.monotonic)


class AsyncCheckpointWriter:
    """One writer thread + a bounded in-flight queue per worker process."""

    def __init__(self, max_inflight: int = 2):
        self.max_inflight = max(1, int(max_inflight))
        self._q: "queue.Queue[Optional[WriteJob]]" = queue.Queue(
            maxsize=self.max_inflight)
        self._errors: List[BaseException] = []
        self._err_lock = threading.Lock()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Condition(self._inflight_lock)
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True)
        self._thread.start()
        self._closed = False

    # -- producer side (train thread) ---------------------------------------

    def submit(self, job: WriteJob) -> float:
        """Enqueue a write; returns seconds spent blocked on backpressure."""
        if self._closed:
            raise RuntimeError("writer is closed")
        self.raise_on_error()
        t0 = time.monotonic()
        with self._inflight_lock:
            self._inflight += 1
        self._gauge()
        self._q.put(job)
        return time.monotonic() - t0

    def raise_on_error(self) -> None:
        """Surface the oldest pending write error ONCE.

        The error is popped as it raises: a transient disk failure must
        not poison every later save for the rest of the run — the caller
        that caught the error keeps checkpointing, and the failed step
        simply never acked (so it can never be committed)."""
        with self._err_lock:
            if not self._errors:
                return
            err = self._errors.pop(0)
        raise ckpt_format.CheckpointError(
            f"async checkpoint write failed: {err!r}") from err

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted write has published (or failed)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._inflight > 0:
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                if deadline is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining if remaining is not None else 1.0)
        return True

    def close(self, timeout: Optional[float] = 120.0) -> None:
        """Flush outstanding writes, stop the thread, surface any error.

        Shutdown is BOUNDED: if the writer is wedged past ``timeout``
        (hung filesystem), the still-queued jobs are dropped — they never
        acked, so the coordinator never commits them — and the failure
        surfaces as a CheckpointError instead of hanging the rank at
        train-fn exit forever.
        """
        if self._closed:
            return
        self._closed = True
        drained = self.wait_idle(timeout)
        if not drained:
            # Wedged writer: make room for the sentinel by dropping the
            # jobs that never started (each is an unlanded, uncommitted
            # save) and record the condition as an error.
            dropped = 0
            while True:
                try:
                    job = self._q.get_nowait()
                except queue.Empty:
                    break
                if job is None:
                    continue
                dropped += 1
                with self._inflight_lock:
                    self._inflight -= 1
                    self._idle.notify_all()
            with self._err_lock:
                self._errors.append(ckpt_format.CheckpointError(
                    f"writer did not drain within {timeout}s at close "
                    f"({dropped} queued save(s) dropped, one write still "
                    f"wedged)"))
            self._gauge()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # writer wedged mid-job with a refilled queue: daemon
            # thread dies with the process; nothing more to flush.
        self._thread.join(timeout=10.0)
        self.raise_on_error()

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    # -- writer thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            try:
                # Bounded get, not a blocking one: when close() timed
                # out on a wedged write it may fail to enqueue the None
                # sentinel (a producer raced the queue slot) — the
                # closed-flag check below still retires this thread once
                # the wedged job finishes, instead of leaking it for the
                # rest of the process (leak found by the sanitizer).
                job = self._q.get(timeout=0.5)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if job is None:
                return
            try:
                self._write_one(job)
            except BaseException as e:  # noqa: BLE001 — surfaced to producer
                with self._err_lock:
                    self._errors.append(e)
                telemetry.note_swallowed("checkpoint.async_writer", e)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
                    self._idle.notify_all()
                self._gauge()

    def _write_one(self, job: WriteJob) -> None:
        publish_shard(job)

    def _gauge(self) -> None:
        telemetry.set_gauge("ray_tpu_ckpt_inflight", float(self.inflight))


def publish_shard(job: WriteJob) -> None:
    """Serialize + publish one shard and run its callback — THE write
    path, shared by the writer thread and synchronous saves (so the
    telemetry, the chaos delay hook, and any future change to the
    publish sequence stay identical in both modes)."""
    delay = float(os.environ.get(_WRITE_DELAY_ENV, "0") or 0)
    if delay > 0:
        time.sleep(delay)
    t0 = time.monotonic()
    index, blob = ckpt_format.build_shard(
        job.snapshot, job.rank, job.world, job.step)
    ckpt_format.write_shard(
        job.dirpath, index, blob,
        skeleton_pkl=job.snapshot.skeleton_pkl if job.rank == 0 else None)
    write_s = time.monotonic() - t0
    telemetry.observe("ray_tpu_ckpt_write_seconds", write_s)
    telemetry.inc("ray_tpu_ckpt_bytes_total", len(blob))
    if job.on_done is not None:
        job.on_done(job, index, blob, write_s)
