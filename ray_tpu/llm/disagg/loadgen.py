"""Open-loop serving load generator (the serve_load bench harness).

Open-loop means arrivals follow a Poisson process pinned to the WALL
CLOCK: a slow server does not slow the generator down, so saturation
shows up as growing queues and shed requests — exactly the regime a
closed-loop (wait-for-completion) driver can never produce, and the one
"millions of users" serving actually lives in.

The workload is the disagg motivation mix: mostly short interactive
prompts plus a fraction of long prompts whose inline prefill would
stall every active decode.  Used by ``bench.py --spec serve_load`` and
the tier-1 saturation smoke test.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ...serve.api import OverloadError


@dataclass
class ServeLoadSpec:
    rps: float = 8.0
    duration_s: float = 5.0
    long_fraction: float = 0.2
    short_prompt: int = 8
    short_max_tokens: int = 16
    long_prompt: int = 192
    long_max_tokens: int = 8
    #: Class names let per-class budgets separate the two populations.
    short_class: str = "interactive"
    long_class: str = "batch"
    seed: int = 0
    #: Wall-clock budget for collecting stragglers after the last
    #: arrival (requests past it count as unfinished, not completed).
    drain_timeout_s: float = 60.0
    #: >0 = prefix-heavy traffic: prompts draw from a fixed pool of
    #: this many distinct prompts (per kind) instead of fresh random
    #: tokens per request — the regime where a fleet's prefix-affinity
    #: routing and per-replica KV caches pay off.  0 = every prompt
    #: unique (the original workload).
    prompt_pool: int = 0


def _percentile_ms(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    return float(np.percentile(np.asarray(samples), q) * 1000.0)


def run_open_loop(server, spec: ServeLoadSpec,
                  vocab_size: int) -> Dict[str, Any]:
    """Drive ``server`` (a DisaggServer) with open-loop Poisson
    arrivals; returns offered/sustained RPS, TTFT/ITL percentiles of
    ADMITTED requests, and the shed breakdown."""
    rng = np.random.default_rng(spec.seed)
    # Pre-draw the whole arrival schedule and request mix so the
    # submit loop does no RNG work on the clock.
    arrivals: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / spec.rps))
        if t >= spec.duration_s:
            break
        arrivals.append(t)
    kinds = rng.random(len(arrivals)) < spec.long_fraction
    prompts = []
    if spec.prompt_pool > 0:
        pool = {
            True: [rng.integers(1, vocab_size, spec.long_prompt).tolist()
                   for _ in range(spec.prompt_pool)],
            False: [rng.integers(1, vocab_size,
                                 spec.short_prompt).tolist()
                    for _ in range(spec.prompt_pool)],
        }
        picks = rng.integers(0, spec.prompt_pool, len(arrivals))
        for long, pick in zip(kinds, picks):
            prompts.append(pool[bool(long)][int(pick)])
    else:
        for long in kinds:
            n = spec.long_prompt if long else spec.short_prompt
            prompts.append(rng.integers(1, vocab_size, n).tolist())

    submitted: List[tuple] = []   # (pub_id, is_long)
    shed_submit = 0
    t0 = time.perf_counter()
    for at, long, prompt in zip(arrivals, kinds, prompts):
        now = time.perf_counter() - t0
        if at > now:
            time.sleep(at - now)   # open loop: wall-clock schedule
        body = {"prompt_tokens": prompt,
                "max_tokens": spec.long_max_tokens if long
                else spec.short_max_tokens,
                "class": spec.long_class if long else spec.short_class}
        try:
            submitted.append((server.submit(body), bool(long)))
        except OverloadError:
            shed_submit += 1
    submit_span = time.perf_counter() - t0

    ttft: List[float] = []
    ttft_hit: List[float] = []    # full prefix hits (fleet replay path)
    ttft_cold: List[float] = []
    prefix_full = 0
    itl: List[float] = []
    completed = 0
    shed_deadline = 0
    errors = 0
    rejected = 0
    unfinished = 0
    drain_deadline = time.perf_counter() + spec.drain_timeout_s
    t_last_done = t0
    for pub_id, _long in submitted:
        left = drain_deadline - time.perf_counter()
        if left <= 0:
            unfinished += 1
            continue
        res = server.result(pub_id, timeout_s=left)
        if res.get("finish_reason") == "shed":
            shed_deadline += 1
            continue
        if "error" in res:
            if res.get("finish_reason") == "timeout":
                unfinished += 1
            else:
                errors += 1
            continue
        if res.get("finish_reason") in ("prompt_too_long",
                                        "kv_capacity_exceeded"):
            # Engine-level rejection: zero tokens produced — counting it
            # as completed would inflate sustained RPS.
            rejected += 1
            continue
        completed += 1
        t_last_done = max(t_last_done, time.perf_counter())
        hit = res.get("prefix_outcome") == "full"
        prefix_full += int(hit)
        if res.get("ttft_s") is not None:
            ttft.append(res["ttft_s"])
            (ttft_hit if hit else ttft_cold).append(res["ttft_s"])
        itl.extend(res.get("itl_s") or [])

    offered = len(arrivals)
    span = max(submit_span, t_last_done - t0, 1e-9)
    shed = shed_submit + shed_deadline
    return {
        "offered": offered,
        "offered_rps": offered / max(spec.duration_s, 1e-9),
        "completed": completed,
        "sustained_rps": completed / span,
        "shed_submit": shed_submit,
        "shed_deadline": shed_deadline,
        "shed_rate": shed / offered if offered else 0.0,
        "errors": errors,
        "rejected": rejected,
        "unfinished": unfinished,
        "ttft_p50_ms": _percentile_ms(ttft, 50),
        "ttft_p99_ms": _percentile_ms(ttft, 99),
        "itl_p50_ms": _percentile_ms(itl, 50),
        "itl_p99_ms": _percentile_ms(itl, 99),
        "itl_samples": len(itl),
        # Fleet prefix-affinity split (None/0 for single-engine servers,
        # which report no prefix_outcome).
        "prefix_hits": prefix_full,
        "prefix_hit_rate": prefix_full / completed if completed else 0.0,
        "ttft_hit_p50_ms": _percentile_ms(ttft_hit, 50),
        "ttft_cold_p50_ms": _percentile_ms(ttft_cold, 50),
    }
