"""Remote-driver client tests (reference analog: python/ray/util/client
tests — ray.init("ray://...") driving a running cluster from another
process)."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

import ray_tpu


CLIENT_SCRIPT = textwrap.dedent("""
    import numpy as np
    import ray_tpu

    ray_tpu.init(address={address!r}, cluster_token={token!r})

    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0
        def inc(self, k=1):
            self.v += k
            return self.v

    # tasks
    assert ray_tpu.get(add.remote(2, 3)) == 5

    # put / get roundtrip, incl. a large (store-promoted) payload
    small = ray_tpu.put({{"x": 1}})
    big = ray_tpu.put(np.arange(200_000, dtype=np.float32))
    assert ray_tpu.get(small)["x"] == 1
    arr = ray_tpu.get(big)
    assert arr.shape == (200_000,) and arr[12345] == 12345.0

    # refs as args (head resolves dependencies)
    r = add.remote(add.remote(1, 1), 3)
    assert ray_tpu.get(r) == 5

    # wait
    refs = [add.remote(i, i) for i in range(4)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=4, timeout=10)
    assert len(ready) == 4 and not not_ready

    # actors
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6

    # control plane (state API) through the client
    nodes = ray_tpu._private.api._control("nodes")
    assert any(n["is_head"] for n in nodes)

    # task errors propagate
    @ray_tpu.remote
    def boom():
        raise ValueError("kapow")
    try:
        ray_tpu.get(boom.remote())
        raise AssertionError("expected TaskError")
    except ray_tpu.TaskError as e:
        assert "kapow" in str(e)

    ray_tpu.shutdown()
    print("CLIENT-OK")
""")


@pytest.fixture(scope="module")
def head():
    token = os.urandom(8).hex().encode()
    rt = ray_tpu.init(num_cpus=4, num_tpus=0, head_port=0,
                      cluster_token=token)
    yield rt, token
    ray_tpu.shutdown()


class TestClient:
    def test_client_session_end_to_end(self, head):
        rt, token = head
        host, port = rt.head_server.address
        script = CLIENT_SCRIPT.format(address=f"{host}:{port}", token=token)
        env = dict(os.environ,
                   RAY_TPU_TPU_CHIPS_PER_HOST_OVERRIDE="0")
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, \
            f"client failed:\nstdout={proc.stdout}\nstderr={proc.stderr}"
        assert "CLIENT-OK" in proc.stdout

    def test_client_disconnect_is_clean(self, head):
        rt, token = head
        host, port = rt.head_server.address
        script = textwrap.dedent(f"""
            import ray_tpu
            ray_tpu.init(address="{host}:{port}", cluster_token={token!r})

            @ray_tpu.remote
            def one():
                return 1
            assert ray_tpu.get(one.remote()) == 1
            ray_tpu.shutdown()
            print("DISC-OK")
        """)
        env = dict(os.environ, RAY_TPU_TPU_CHIPS_PER_HOST_OVERRIDE="0")
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "DISC-OK" in proc.stdout
        # The head survives a client hangup: local API still works.
        @ray_tpu.remote
        def two():
            return 2
        assert ray_tpu.get(two.remote()) == 2
