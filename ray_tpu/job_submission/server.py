"""REST API for jobs + cluster state (the dashboard-head slice that serves
the CLI and JobSubmissionClient).

Reference: dashboard/modules/job/job_head.py (REST routes
/api/jobs/*) and dashboard/head.py (aiohttp app hosting modules).
"""

from __future__ import annotations

from ray_tpu._private import aioloop as _aioloop

import asyncio
import threading
from typing import Any, Dict, Optional

from .manager import JobManager


class JobServer:
    """aiohttp server on a background thread; thread-safe over the manager
    by funneling manager calls through an executor (the manager does
    blocking ray_tpu.get calls)."""

    def __init__(self, manager: JobManager, port: int = 0,
                 host: str = "127.0.0.1"):
        self.manager = manager
        self.host = host
        self.port = port
        self.bound_port: Optional[int] = None
        self._started = threading.Event()
        self._loop = None
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="ray_tpu-job-server")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("job server failed to start")

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.bound_port}"

    def _serve(self):
        from aiohttp import web

        mgr = self.manager

        def call(fn, *args, **kwargs):
            return asyncio.get_event_loop().run_in_executor(
                None, lambda: fn(*args, **kwargs))

        async def submit(request: "web.Request"):
            body = await request.json()
            try:
                sid = await call(
                    mgr.submit_job,
                    entrypoint=body["entrypoint"],
                    submission_id=body.get("submission_id"),
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"))
                return web.json_response({"submission_id": sid})
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": repr(e)}, status=400)

        async def list_jobs(request):
            infos = await call(mgr.list_jobs)
            return web.json_response([i.to_dict() for i in infos])

        async def job_info(request):
            sid = request.match_info["sid"]
            try:
                info = await call(mgr.get_job_info, sid)
            except KeyError:
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response(info.to_dict())

        async def job_logs(request):
            sid = request.match_info["sid"]
            try:
                logs = await call(mgr.get_job_logs, sid)
            except KeyError:
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response({"logs": logs})

        async def job_stop(request):
            sid = request.match_info["sid"]
            try:
                stopped = await call(mgr.stop_job, sid)
            except KeyError:
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response({"stopped": stopped})

        def _goodput():
            from ray_tpu.util import telemetry
            return telemetry.goodput_summary()

        def _watchdog_verdict():
            import json as _json

            from ray_tpu._private.api import _control
            from ray_tpu.train.watchdog import VERDICT_KV_KEY
            raw = _control("kv_get", VERDICT_KV_KEY)
            if not raw:
                return None
            try:
                return _json.loads(raw)
            except Exception:  # noqa: BLE001
                return None

        def _mesh_status():
            from ray_tpu.train.mesh.runtime import read_mesh_status
            return read_mesh_status()

        def _autoscaler_status():
            import json as _json

            from ray_tpu._private.api import _control
            from ray_tpu.autoscaler import AUTOSCALER_KV_KEY
            raw = _control("kv_get", AUTOSCALER_KV_KEY)
            if not raw:
                return None
            try:
                return _json.loads(raw)
            except Exception:  # noqa: BLE001
                return None

        async def cluster_status(request):
            from ray_tpu._private.api import _control
            import ray_tpu
            payload: Dict[str, Any] = {
                "nodes": await call(_control, "nodes"),
                "total_resources": await call(ray_tpu.cluster_resources),
                "available_resources":
                    await call(ray_tpu.available_resources),
                "actors": await call(_control, "list_actors"),
                "task_summary": await call(_control, "summarize_tasks"),
                # Operator health at a glance (`ray-tpu status`): live
                # goodput ratio + the watchdog's last verdict.
                "goodput": await call(_goodput),
                "watchdog": await call(_watchdog_verdict),
                # Live SPMD mesh shape of the last-formed train group
                # (train/mesh runtime; None before any mesh-parallel run).
                "mesh": await call(_mesh_status),
                # Autoscaler reconcile view (pending pre-buys next to
                # the goodput they protect; None without an autoscaler).
                "autoscaler": await call(_autoscaler_status),
            }
            return web.json_response(payload)

        async def cluster_stacks(request):
            from ray_tpu._private.api import _control
            timeout = request.query.get("timeout_s")
            if timeout:
                try:
                    timeout_f = float(timeout)
                except ValueError:
                    return web.json_response(
                        {"error": "bad timeout_s"}, status=400)
                dump = await call(_control, "stack_dump", timeout_f)
            else:
                dump = await call(_control, "stack_dump")
            return web.json_response(dump)

        async def cluster_debug_dump(request):
            from ray_tpu._private.api import _control
            reason = request.query.get("reason", "manual")
            path = await call(_control, "debug_dump", reason)
            return web.json_response({"path": path})

        async def cluster_profile(request):
            """On-demand cluster profile (`ray-tpu profile`): blocks
            for the capture window in the executor, returns the merged
            clock-aligned Chrome trace (+ its on-disk path)."""
            from ray_tpu._private.api import _control
            try:
                duration = float(request.query.get("duration_s", "2"))
                hz = float(request.query.get("hz", "67"))
            except ValueError:
                return web.json_response(
                    {"error": "bad duration_s/hz"}, status=400)
            jax_profile = request.query.get("jax") == "1"
            out = await call(_control, "profile", duration, hz,
                             jax_profile)
            if request.query.get("include_trace") == "0":
                out = {k: v for k, v in out.items() if k != "trace"}
            return web.json_response(out)

        async def cluster_drain_node(request):
            """Operator-initiated drain (`ray-tpu drain`): the node
            becomes unschedulable and drain-aware controllers evacuate
            their work before the deadline."""
            from ray_tpu._private.api import _control
            node_id = request.query.get("node_id", "")
            reason = request.query.get("reason", "manual")
            try:
                deadline_s = float(request.query.get("deadline_s", "30"))
            except ValueError:
                return web.json_response(
                    {"error": "bad deadline_s"}, status=400)
            if request.query.get("undrain") == "1":
                ok = await call(_control, "undrain_node", node_id)
            else:
                ok = await call(_control, "drain_node", node_id,
                                deadline_s, reason)
            if not ok:
                return web.json_response(
                    {"error": f"no alive node {node_id!r}"}, status=404)
            return web.json_response({"ok": True})

        async def cluster_sched(request):
            """Control-plane telescope (`ray-tpu sched`): queue depths,
            decision totals/rates, event-buffer health; ?decisions=N
            also returns the last N decision-ring records."""
            from ray_tpu._private.api import _control
            out = {"stats": await call(_control, "sched_stats")}
            try:
                n = int(request.query.get("decisions", "0"))
            except ValueError:
                return web.json_response(
                    {"error": "bad decisions"}, status=400)
            if n > 0:
                out["decisions"] = await call(
                    _control, "sched_decisions", None, n)
            return web.json_response(out)

        async def cluster_task_explain(request):
            """`ray-tpu task why <id>`: why is this task pending / why
            did it land where it did (id prefix ok)."""
            from ray_tpu._private.api import _control
            task_id = request.query.get("task_id", "")
            if not task_id:
                return web.json_response(
                    {"error": "task_id required"}, status=400)
            return web.json_response(
                await call(_control, "explain_task", task_id))

        async def cluster_memory(request):
            """Data-plane telescope (`ray-tpu memory`): per-node object
            store occupancy, top objects by size, leak candidates."""
            from ray_tpu._private.api import _control
            try:
                top_n = int(request.query.get("top_n", "10"))
            except ValueError:
                return web.json_response(
                    {"error": "bad top_n"}, status=400)
            return web.json_response(
                await call(_control, "memory_summary", top_n))

        async def cluster_object_explain(request):
            """`ray-tpu obj why <id>`: one object's location, producer
            and store lifecycle (id prefix ok)."""
            from ray_tpu._private.api import _control
            object_id = request.query.get("object_id", "")
            if not object_id:
                return web.json_response(
                    {"error": "object_id required"}, status=400)
            return web.json_response(
                await call(_control, "explain_object", object_id))

        async def timeline(request):
            from ray_tpu._private.api import _control
            return web.json_response(await call(_control, "timeline"))

        async def metrics(request):
            from ray_tpu.util import metrics as m
            text = await call(m.prometheus_text)
            return web.Response(text=text,
                                content_type="text/plain")

        async def cluster_metrics_query(request):
            """`ray-tpu metrics query`: windowed aggregate from the
            head's time-series store (ray_tpu.metricsview)."""
            from ray_tpu._private.api import _control
            from ray_tpu.metricsview import parse_tag_args, validate_agg
            name = request.query.get("name", "")
            if not name:
                return web.json_response(
                    {"error": "name required"}, status=400)
            agg = request.query.get("agg", "avg")
            try:
                window_s = float(request.query.get("window", "60"))
                tags = parse_tag_args(request.query.getall("tag", []))
                if not validate_agg(agg):
                    raise ValueError(
                        f"unknown agg {agg!r} (rate|delta|avg|min|max|"
                        f"last|pNN)")
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            return web.json_response(await call(
                _control, "metrics_query", name, window_s, agg, tags))

        async def cluster_metrics_history(request):
            """`ray-tpu metrics history`: recent [age_s, value] rows per
            matching series (sparkline shape)."""
            from ray_tpu._private.api import _control
            from ray_tpu.metricsview import parse_tag_args
            name = request.query.get("name", "")
            if not name:
                return web.json_response(
                    {"error": "name required"}, status=400)
            try:
                window_s = float(request.query.get("window", "300"))
                max_points = int(request.query.get("points", "240"))
                tags = parse_tag_args(request.query.getall("tag", []))
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            return web.json_response(await call(
                _control, "metrics_history", name, window_s, tags,
                max_points))

        async def cluster_metrics_series(request):
            from ray_tpu._private.api import _control
            return web.json_response(
                await call(_control, "metrics_series"))

        async def cluster_alerts(request):
            """`ray-tpu alerts`: SLO objective states + recent
            transitions from the burn-rate engine."""
            from ray_tpu._private.api import _control
            try:
                recent = int(request.query.get("recent", "50"))
            except ValueError:
                return web.json_response(
                    {"error": "bad recent"}, status=400)
            return web.json_response(
                await call(_control, "alerts", recent))

        async def cluster_serve_fleet(request):
            """`ray-tpu serve status`: published decode-fleet snapshots
            (per-replica load + prefix-cache stats, autoscale state)."""
            import json as _json

            from ray_tpu._private.api import _control

            def read():
                fleets = []
                for key in sorted(_control("kv_keys", "serve:fleet:")):
                    blob = _control("kv_get", key)
                    if not blob:
                        continue
                    try:
                        fleets.append(_json.loads(blob.decode()))
                    except Exception:
                        continue
                return {"fleets": fleets}

            return web.json_response(await call(read))

        async def cluster_slo(request):
            """POST: replace the SLO objective set (JSON list of
            objective specs); GET: list the registered specs."""
            from ray_tpu._private.api import _control
            if request.method == "POST":
                try:
                    body = await request.json()
                    n = await call(_control, "slo_set", list(body))
                except Exception as e:  # noqa: BLE001 — client payload
                    return web.json_response(
                        {"error": repr(e)}, status=400)
                return web.json_response({"objectives": n})
            return web.json_response(await call(_control, "slo_list"))

        async def main():
            app = web.Application()
            app.router.add_post("/api/jobs/", submit)
            app.router.add_get("/api/jobs/", list_jobs)
            app.router.add_get("/api/jobs/{sid}", job_info)
            app.router.add_get("/api/jobs/{sid}/logs", job_logs)
            app.router.add_post("/api/jobs/{sid}/stop", job_stop)
            app.router.add_get("/api/cluster/status", cluster_status)
            app.router.add_get("/api/cluster/timeline", timeline)
            app.router.add_get("/api/cluster/stacks", cluster_stacks)
            app.router.add_post("/api/cluster/debug_dump",
                                cluster_debug_dump)
            app.router.add_post("/api/cluster/profile", cluster_profile)
            app.router.add_post("/api/cluster/drain_node",
                                cluster_drain_node)
            app.router.add_get("/api/cluster/sched", cluster_sched)
            app.router.add_get("/api/cluster/task_explain",
                               cluster_task_explain)
            app.router.add_get("/api/cluster/memory", cluster_memory)
            app.router.add_get("/api/cluster/object_explain",
                               cluster_object_explain)
            app.router.add_get("/api/cluster/metrics/query",
                               cluster_metrics_query)
            app.router.add_get("/api/cluster/metrics/history",
                               cluster_metrics_history)
            app.router.add_get("/api/cluster/metrics/series",
                               cluster_metrics_series)
            app.router.add_get("/api/cluster/alerts", cluster_alerts)
            app.router.add_get("/api/cluster/serve/fleet",
                               cluster_serve_fleet)
            app.router.add_get("/api/cluster/slo", cluster_slo)
            app.router.add_post("/api/cluster/slo", cluster_slo)
            app.router.add_get("/metrics", metrics)
            app.router.add_get(
                "/-/healthz", lambda r: web.json_response({"ok": True}))
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.bound_port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            while True:
                await asyncio.sleep(3600)

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(main())
        except Exception:
            pass
        finally:
            # Executor + loop retirement shared across the three
            # daemon-loop servers (see _private/aioloop.py).
            _aioloop.shutdown_loop(self._loop)

    def stop(self):
        _aioloop.stop_loop_thread(self._loop, self._thread)
