"""Built-in system telemetry: the canonical catalog of framework metrics.

Reference: the dashboard-agent's built-in metric export
(python/ray/_private/metrics_agent.py — tasks, serve request latency,
autoscaler state — and src/ray/observability/open_telemetry_metric_recorder.h).
User code defines its own metrics through ``util/metrics.py``; the
framework's OWN hot paths (serve routing, the LLM engine, the train
controller, the data executor) record through this module instead, so a
single ``GET /metrics`` scrape or ``export_otlp_json`` carries both.

Three pieces:

* ``CATALOG`` — every built-in metric, named ``ray_tpu_<subsystem>_<what>``,
  with type/description/tags declared in ONE place.  Instrumentation sites
  call ``counter(name)`` / ``gauge(name)`` / ``histogram(name)``, which
  lazily instantiate against the catalog — a typo'd or undeclared name
  raises instead of silently minting a new series
  (tests/test_telemetry_catalog.py locks the naming scheme down).
* ``profile_span(name, category)`` — a cheap span recorder feeding the
  chrome-trace timeline buffer (``_private/events.py``).  On the driver
  it is a direct buffer append; in a worker it is a FIRE-AND-FORGET
  control frame (no reply round-trip — safe on per-decode-step hot
  paths); with no runtime at all it is a no-op, so library code (the
  inference engine under bench.py) can stay instrumented unconditionally.
* ``GoodputTracker`` — partitions a training run's wall time into
  productive-step vs init/checkpoint/restart/idle (MegaScale-style
  goodput accounting) and exposes ``ray_tpu_train_goodput_ratio``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from . import metrics as _metrics

# Bucket sets tuned per family: latencies are sub-second-centric; batch
# sizes / step times are coarser.
_LATENCY_BUCKETS = [0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0]
_SIZE_BUCKETS = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0]
_STEP_BUCKETS = [0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
                 120.0, 300.0, 600.0]

#: name -> {"type", "description", "tag_keys", "boundaries"?}
CATALOG: Dict[str, Dict[str, Any]] = {
    # -- serve -------------------------------------------------------------
    "ray_tpu_serve_requests_total": {
        "type": "counter", "tag_keys": ("deployment",),
        "description": "Requests routed to a deployment replica."},
    "ray_tpu_serve_request_errors_total": {
        "type": "counter", "tag_keys": ("deployment",),
        "description": "Requests that raised at the ingress/handle layer."},
    "ray_tpu_serve_request_latency_seconds": {
        "type": "histogram", "tag_keys": ("deployment",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "End-to-end handle request latency (route -> "
                       "result materialized)."},
    "ray_tpu_serve_queue_wait_seconds": {
        "type": "histogram", "tag_keys": ("method",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Time a @serve.batch item waited in the queue "
                       "before its batch started executing."},
    "ray_tpu_serve_batch_size": {
        "type": "histogram", "tag_keys": ("method",),
        "boundaries": _SIZE_BUCKETS,
        "description": "Items per executed @serve.batch batch."},
    "ray_tpu_serve_replicas": {
        "type": "gauge", "tag_keys": ("deployment",),
        "description": "Live replica count per deployment (controller "
                       "view)."},
    "ray_tpu_serve_ongoing_requests": {
        "type": "gauge", "tag_keys": ("deployment",),
        "description": "This process's in-flight requests per deployment "
                       "(router view)."},
    "ray_tpu_serve_shed_total": {
        "type": "counter", "tag_keys": ("deployment",),
        "description": "Handle-path requests rejected by the "
                       "max_queued_requests admission bound (retriable "
                       "OverloadError instead of unbounded queueing)."},
    # -- serve: decode fleet (ray_tpu.llm.fleet) ---------------------------
    "ray_tpu_serve_replica_count": {
        "type": "gauge", "tag_keys": ("fleet",),
        "description": "Accepting decode replicas in a serving fleet "
                       "(FleetServer view; draining/dead excluded)."},
    "ray_tpu_serve_prefix_hit_total": {
        "type": "counter", "tag_keys": ("outcome",),
        "description": "Fleet routing outcomes per dispatched request: "
                       "full (exact prompt cached, prefill skipped), "
                       "partial (prefix overlap steered placement), "
                       "miss (load-only placement)."},
    "ray_tpu_serve_rebalance_total": {
        "type": "counter", "tag_keys": (),
        "description": "Requests whose prefix affinity was overridden "
                       "by the load-imbalance watermark (routed by load "
                       "instead of cache locality)."},
    "ray_tpu_serve_replica_scale_total": {
        "type": "counter", "tag_keys": ("direction",),
        "description": "Fleet replica scale actions (up = spawn/"
                       "backfill, down = drain-then-remove), autoscaler "
                       "or manual."},
    # -- llm ---------------------------------------------------------------
    "ray_tpu_llm_ttft_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Time to first token: request add -> first output "
                       "token sampled (includes queueing + prefill)."},
    "ray_tpu_llm_decode_token_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Per-token decode latency (batched step wall time; "
                       "chunked steps attribute wall/steps per token)."},
    "ray_tpu_llm_tokens_total": {
        "type": "counter", "tag_keys": ("kind",),
        "description": "Tokens processed by the engine "
                       "(kind=prompt|decode)."},
    "ray_tpu_llm_kv_page_occupancy": {
        "type": "gauge", "tag_keys": (),
        "description": "Fraction of KV-cache pages allocated (0..1)."},
    "ray_tpu_llm_active_slots": {
        "type": "gauge", "tag_keys": (),
        "description": "Decode slots with a running request."},
    "ray_tpu_llm_requests_finished_total": {
        "type": "counter", "tag_keys": ("reason",),
        "description": "Engine requests finished, by finish_reason "
                       "(stop|length|prompt_too_long|"
                       "kv_capacity_exceeded|cancelled)."},
    "ray_tpu_llm_preemptions_total": {
        "type": "counter", "tag_keys": (),
        "description": "Requests evicted mid-flight (cancel/timeout "
                       "releasing an occupied slot)."},
    "ray_tpu_llm_waiting_requests": {
        "type": "gauge", "tag_keys": (),
        "description": "Requests queued for admission (KV/slot "
                       "backpressure depth)."},
    "ray_tpu_llm_admission_queue_depth": {
        "type": "gauge", "tag_keys": ("class",),
        "description": "Requests held in the SLO router's bounded "
                       "admission queue, per request class (disagg "
                       "router; ahead of engine admission)."},
    "ray_tpu_llm_shed_total": {
        "type": "counter", "tag_keys": ("reason",),
        "description": "Requests shed by SLO-aware admission control "
                       "(reason=queue_full|class_budget|backpressure|"
                       "deadline).  Shedding is a retriable overload "
                       "error, never a silent timeout."},
    "ray_tpu_llm_kv_transfer_bytes_total": {
        "type": "counter", "tag_keys": (),
        "description": "KV-cache bytes handed off from prefill to "
                       "decode workers (disagg page-blob transfers)."},
    "ray_tpu_llm_kv_transfer_seconds": {
        "type": "histogram", "tag_keys": ("op",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Prefill->decode KV handoff latency "
                       "(op=export|import: object-store publish / "
                       "decode-side page scatter)."},
    "ray_tpu_llm_prefill_chunks_total": {
        "type": "counter", "tag_keys": (),
        "description": "Chunked-prefill chunks executed (single-engine "
                       "disagg-off fallback: long prompts sliced across "
                       "decode steps)."},
    # -- train -------------------------------------------------------------
    "ray_tpu_train_step_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _STEP_BUCKETS,
        "description": "Wall time between consecutive rank-0 "
                       "train.report() calls (one reporting step)."},
    "ray_tpu_train_tokens_total": {
        "type": "counter", "tag_keys": (),
        "description": "Training tokens, from report() metrics carrying "
                       "a tokens/num_tokens/tokens_per_step key."},
    "ray_tpu_train_reports_total": {
        "type": "counter", "tag_keys": (),
        "description": "train.report() calls across all ranks."},
    "ray_tpu_train_checkpoint_seconds": {
        "type": "histogram", "tag_keys": ("op",),
        "boundaries": _STEP_BUCKETS,
        "description": "Checkpoint pytree save/restore duration "
                       "(op=save|restore)."},
    "ray_tpu_train_worker_restarts_total": {
        "type": "counter", "tag_keys": (),
        "description": "Train workers torn down and restarted after a "
                       "failure."},
    "ray_tpu_train_urgent_ckpt_total": {
        "type": "counter", "tag_keys": (),
        "description": "Urgent checkpoint flushes triggered by a drain "
                       "notice (async writer drained + emergency "
                       "replicas pushed before the node dies)."},
    "ray_tpu_train_restart_backoff_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _STEP_BUCKETS,
        "description": "Backoff slept between group re-formations after "
                       "a failure (bounded exponential; resets once an "
                       "incarnation proves stable)."},
    "ray_tpu_train_goodput_ratio": {
        "type": "gauge", "tag_keys": (),
        "description": "Productive-step wall time over total run wall "
                       "time (goodput accounting; see GoodputTracker)."},
    "ray_tpu_train_step_phase_seconds": {
        "type": "histogram", "tag_keys": ("phase",),
        "boundaries": _STEP_BUCKETS,
        "description": "Per-step device-time attribution: seconds each "
                       "reporting step spent in a declared phase "
                       "(data_wait|h2d|compute|collective|ckpt_block|"
                       "other; ray_tpu.train.step_phase fences with "
                       "block_until_ready at phase boundaries so async "
                       "dispatch cannot smear compute into the next "
                       "phase)."},
    "ray_tpu_train_hbm_used_bytes": {
        "type": "gauge", "tag_keys": ("device",),
        "description": "Per-device accelerator memory in use (jax "
                       "memory_stats; absent on backends that do not "
                       "report it).  Creeping HBM is the classic silent "
                       "step-time killer."},
    "ray_tpu_train_hbm_peak_bytes": {
        "type": "gauge", "tag_keys": ("device",),
        "description": "Per-device peak accelerator memory since process "
                       "start (jax memory_stats peak_bytes_in_use)."},
    "ray_tpu_train_straggler_total": {
        "type": "counter", "tag_keys": (),
        "description": "Watchdog straggler verdicts: a rank's step time "
                       "exceeded the configured multiple of the "
                       "across-rank median (one per incident)."},
    "ray_tpu_train_hang_total": {
        "type": "counter", "tag_keys": (),
        "description": "Watchdog hang verdicts: a rank produced no "
                       "report within the hang deadline (one per "
                       "incident)."},
    "ray_tpu_train_mesh_axis_size": {
        "type": "gauge", "tag_keys": ("axis",),
        "description": "Live SPMD mesh axis sizes of the current train "
                       "worker group (axis=dp|fsdp|tp|sp|ep|pp; "
                       "refreshed at every group (re)formation — an "
                       "elastic resize shows up as the shape changing)."},
    "ray_tpu_train_param_shard_bytes": {
        "type": "gauge", "tag_keys": (),
        "description": "This process's addressable parameter-shard "
                       "bytes after train.shard() / a mesh restore "
                       "(~ total/N when parameters are truly sharded; "
                       "~ total means the model is replicated)."},
    "ray_tpu_train_upsize_total": {
        "type": "counter", "tag_keys": (),
        "description": "Elastic upsizes: the worker group tore down at a "
                       "checkpoint boundary and re-formed LARGER because "
                       "joined capacity fit a bigger mesh-tileable world "
                       "(the add_node/pre-buy-arrival reaction; "
                       "downsizes ride the drain/failure paths)."},
    "ray_tpu_train_mesh_reshapes_total": {
        "type": "counter", "tag_keys": (),
        "description": "Mesh reshape events: a worker group re-formed "
                       "at a different mesh shape than its predecessor, "
                       "or a checkpoint restored onto a mesh other than "
                       "the one that saved it (resharding restore)."},
    # -- ckpt (distributed checkpointing subsystem) ------------------------
    "ray_tpu_ckpt_save_blocking_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _STEP_BUCKETS,
        "description": "Train-thread time a save actually stole: the "
                       "device->host snapshot plus any write-queue "
                       "backpressure wait (async saves) or the full "
                       "serialize+write (sync saves)."},
    "ray_tpu_ckpt_write_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _STEP_BUCKETS,
        "description": "Background shard serialize+publish duration "
                       "(tmp-file + atomic rename, off the step path)."},
    "ray_tpu_ckpt_bytes_total": {
        "type": "counter", "tag_keys": (),
        "description": "Checkpoint shard bytes published by this "
                       "process."},
    "ray_tpu_ckpt_inflight": {
        "type": "gauge", "tag_keys": (),
        "description": "Async checkpoint saves queued or writing "
                       "(bounded by CheckpointConfig.max_inflight; "
                       "pinned at the bound = the saver outruns the "
                       "disk and backpressure is biting)."},
    "ray_tpu_ckpt_restore_seconds": {
        "type": "histogram", "tag_keys": ("source",),
        "boundaries": _STEP_BUCKETS,
        "description": "Checkpoint restore duration, by shard source "
                       "(source=disk|replica)."},
    "ray_tpu_ckpt_replica_restores_total": {
        "type": "counter", "tag_keys": (),
        "description": "Restores that used in-memory emergency replica "
                       "shards instead of (or ahead of) cold storage."},
    # -- node (drain / preemption lifecycle) -------------------------------
    "ray_tpu_node_preempted_total": {
        "type": "counter", "tag_keys": (),
        "description": "Nodes the cloud took away while they were "
                       "RUNNING/JOINED (spot reclaim, maintenance) — "
                       "every preemption is counted, graceful or not."},
    "ray_tpu_node_drain_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _STEP_BUCKETS,
        "description": "Drain-notice-to-node-death duration: how much of "
                       "the advertised deadline the cluster actually got "
                       "to evacuate work."},
    "ray_tpu_node_draining": {
        "type": "gauge", "tag_keys": (),
        "description": "Nodes currently draining (unschedulable for new "
                       "leases, waiting for work to evacuate)."},
    # -- autoscaler (goodput-driven scaling + pre-buy) ---------------------
    "ray_tpu_autoscaler_prebuy_total": {
        "type": "counter", "tag_keys": (),
        "description": "Replacement capacity bought at preemption-NOTICE "
                       "time (before the victim's deadline, not after its "
                       "death) so the post-drain reform can upsize back "
                       "instead of limping at n-1."},
    "ray_tpu_autoscaler_goodput_scale_events_total": {
        "type": "counter", "tag_keys": ("direction",),
        "description": "Scaling actions taken by the goodput-driven "
                       "policy (direction=up: capacity bought after the "
                       "goodput ratio sagged below the configured floor "
                       "for the sustain window; direction=down: surplus "
                       "drained back once goodput recovered and nodes "
                       "sat idle)."},
    "ray_tpu_autoscaler_pending_prebuys": {
        "type": "gauge", "tag_keys": (),
        "description": "Pre-bought replacement nodes launched but not "
                       "yet joined (the `ray-tpu status` pre-buy line; "
                       "pinned at max_pending_prebuys = a notice storm "
                       "is being rate-limited)."},
    # -- slice (multi-slice reservation lifecycle) -------------------------
    "ray_tpu_slice_drains_total": {
        "type": "counter", "tag_keys": (),
        "description": "Per-slice drains: one slice of a multi-slice "
                       "SlicePlacementGroup fenced + evacuated while the "
                       "other slices' committed bundles stay untouched."},
    # -- profiler (cluster-wide performance profiling subsystem) -----------
    "ray_tpu_profiler_compile_total": {
        "type": "counter", "tag_keys": ("fn",),
        "description": "XLA compilations attributed to a tracked "
                       "call site (jax.monitoring backend_compile "
                       "events; fn=<site name>)."},
    "ray_tpu_profiler_compile_seconds": {
        "type": "histogram", "tag_keys": ("fn",),
        "boundaries": _STEP_BUCKETS,
        "description": "Seconds spent in XLA backend compilation per "
                       "tracked call site."},
    "ray_tpu_profiler_recompiles_total": {
        "type": "counter", "tag_keys": ("fn",),
        "description": "POST-WARMUP recompilations: a tracked site that "
                       "had reached steady state compiled again (shape/"
                       "dtype churn — the #1 silent TPU step-time "
                       "regression).  Each also logs a once-per-site "
                       "warning naming the offending shapes."},
    "ray_tpu_profiler_captures_total": {
        "type": "counter", "tag_keys": (),
        "description": "On-demand cluster profile captures served "
                       "(`ray-tpu profile` / POST /api/profile / "
                       "flight-recorder auto-attach)."},
    # -- sched (control-plane telescope: scheduler decision tracing) -------
    "ray_tpu_sched_decisions_total": {
        "type": "counter", "tag_keys": ("kind",),
        "description": "Scheduler decisions by kind (inline|loop|"
                       "exchange|pipeline|reject|infeasible|pg_commit|"
                       "pg_reject).  Flushed from the decision ring's "
                       "plain-int tallies by the rate-limited publisher "
                       "— never a counter op on the placement hot "
                       "path."},
    "ray_tpu_sched_stage_wait_seconds": {
        "type": "histogram", "tag_keys": ("stage",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Task lifecycle stage waits (stage=deps|queue|"
                       "dispatch|startup|run), derived monotonic-minus-"
                       "monotonic from the TaskEvent ring's per-"
                       "transition stamps.  A fat 'queue' tail means "
                       "placement is the bottleneck; a fat 'dispatch' "
                       "tail means arg resolution / the worker pipe "
                       "is."},
    "ray_tpu_sched_placement_attempts": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _SIZE_BUCKETS,
        "description": "Placement rounds a task needed before it was "
                       "booked onto a node (1 = placed on first look; "
                       "the tail counts retry pressure from full/"
                       "draining clusters)."},
    "ray_tpu_sched_pg_commit_seconds": {
        "type": "histogram", "tag_keys": (),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Placement-group two-phase commit latency: "
                       "register -> every bundle committed (includes "
                       "the PENDING retry window while capacity is "
                       "awaited; node-death re-plans re-enter here)."},
    "ray_tpu_sched_queue_depth": {
        "type": "gauge", "tag_keys": ("queue",),
        "description": "Scheduler queue depths (queue=ready|"
                       "waiting_deps|infeasible|pending_pgs), refreshed "
                       "~1/s by the scheduler loop's metrics "
                       "publisher."},
    # -- internal ----------------------------------------------------------
    "ray_tpu_internal_swallowed_errors_total": {
        "type": "counter", "tag_keys": ("where",),
        "description": "Control-plane exceptions intentionally swallowed "
                       "(best-effort paths), by call site.  A climbing "
                       "series names the subsystem eating errors."},
    "ray_tpu_lock_wait_seconds": {
        "type": "histogram", "tag_keys": ("site",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Sampled lock-acquire wait by creation site "
                       "(~1/64th of releases), from the opt-in "
                       "contention profiler (RAY_TPU_LOCK_PROFILE=1 / "
                       "RAY_TPU_DEBUG_LOCKS=1).  A fat tail names a "
                       "lock threads queue on."},
    "ray_tpu_lock_hold_seconds": {
        "type": "histogram", "tag_keys": ("site",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Sampled lock hold time by creation site "
                       "(~1/64th of releases), from the opt-in "
                       "contention profiler.  Long holds on a "
                       "contended site are the thing to shrink first "
                       "(see ray-tpu lint --lock-report)."},
    # -- jax (host-sync tripwire) ------------------------------------------
    "ray_tpu_jax_host_sync_total": {
        "type": "counter", "tag_keys": ("site",),
        "description": "Implicit jax device->host syncs by call site "
                       "(float()/.item()/np.asarray() on device arrays), "
                       "from the opt-in tripwire (RAY_TPU_SYNC_DEBUG=1).  "
                       "Published in batches of 64 per site; a hot site "
                       "in a step/decode loop is an RT502 to fix."},
    "ray_tpu_jax_host_sync_seconds": {
        "type": "histogram", "tag_keys": ("site",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Sampled blocked-time of implicit device->host "
                       "syncs by call site (~1/64th of syncs), from the "
                       "opt-in tripwire.  The histogram shows how long "
                       "the host thread stalls waiting on the device "
                       "(see ray-tpu lint --sync-report)."},
    # -- metricsview (time-series backplane) -------------------------------
    "ray_tpu_metricsview_points_total": {
        "type": "counter", "tag_keys": (),
        "description": "Points appended to the head's metrics "
                       "time-series store (post-downsample: a burst of "
                       "flushes inside one interval stores one "
                       "point)."},
    "ray_tpu_metricsview_dropped_total": {
        "type": "counter", "tag_keys": (),
        "description": "Store points lost to ring eviction plus series "
                       "refused over the metricsview_max_series cap — "
                       "a climbing rate means history is shorter than "
                       "the configured retention."},
    # -- alerts (SLO burn-rate engine) -------------------------------------
    "ray_tpu_alerts_firing": {
        "type": "gauge", "tag_keys": (),
        "description": "SLO objectives currently in the firing state "
                       "(fast AND slow burn-rate windows breached)."},
    "ray_tpu_alerts_transitions_total": {
        "type": "counter", "tag_keys": ("state",),
        "description": "Alert state-machine transitions by destination "
                       "state (state=pending|firing|resolved|ok)."},
    # -- data --------------------------------------------------------------
    "ray_tpu_data_block_seconds": {
        "type": "histogram", "tag_keys": ("operator",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Per-block processing time in the streaming "
                       "executor (operator=map|reduce)."},
    "ray_tpu_data_rows_total": {
        "type": "counter", "tag_keys": ("operator",),
        "description": "Rows produced by data-pipeline operators."},
    "ray_tpu_data_blocks_total": {
        "type": "counter", "tag_keys": ("operator",),
        "description": "Blocks processed by data-pipeline operators."},
    # -- store (object store + transfer data plane; see storeview/) --------
    "ray_tpu_store_used_bytes": {
        "type": "gauge", "tag_keys": ("node",),
        "description": "Object-store bytes in use per node (arena/shm "
                       "occupancy; spilled objects excluded)."},
    "ray_tpu_store_capacity_bytes": {
        "type": "gauge", "tag_keys": ("node",),
        "description": "Configured object-store capacity per node."},
    "ray_tpu_store_pinned_bytes": {
        "type": "gauge", "tag_keys": ("node",),
        "description": "Bytes held by reader-pinned objects per node "
                       "(never evictable/spillable while pinned)."},
    "ray_tpu_store_spilled_bytes": {
        "type": "gauge", "tag_keys": ("node",),
        "description": "Bytes currently spilled to disk per node."},
    "ray_tpu_store_objects": {
        "type": "gauge", "tag_keys": ("node",),
        "description": "Objects tracked by the store per node (in "
                       "memory + spilled)."},
    "ray_tpu_store_ops_total": {
        "type": "counter", "tag_keys": ("op",),
        "description": "Store operations, from the lifecycle ring's "
                       "per-kind tallies (op=create|seal|get|pin|unpin|"
                       "delete), published by the head's metrics-flush "
                       "piggyback."},
    "ray_tpu_store_spill_ops_total": {
        "type": "counter", "tag_keys": ("op",),
        "description": "Memory-pressure events "
                       "(op=spill|restore|evict)."},
    "ray_tpu_store_spill_reclaimed_bytes_total": {
        "type": "counter", "tag_keys": (),
        "description": "Orphaned spill-file bytes deleted by the "
                       "boot/shutdown GC sweep (files left by dead "
                       "store processes)."},
    "ray_tpu_store_transfer_bytes_total": {
        "type": "counter", "tag_keys": ("direction",),
        "description": "Cross-node object payload bytes moved by this "
                       "process (direction=push|pull: push = served by "
                       "the local data server, pull = localized from a "
                       "remote node)."},
    "ray_tpu_store_transfer_seconds": {
        "type": "histogram", "tag_keys": ("op",),
        "boundaries": _LATENCY_BUCKETS,
        "description": "Cross-node transfer latency (op=push|pull; pull "
                       "= resolve + fetch + local put of one object)."},
}

_instances_lock = threading.Lock()
_instances: Dict[str, _metrics.Metric] = {}


def _get(name: str, expect_type: str) -> _metrics.Metric:
    spec = CATALOG.get(name)
    if spec is None:
        raise KeyError(f"{name!r} is not in the built-in telemetry catalog")
    if spec["type"] != expect_type:
        raise TypeError(f"{name!r} is a {spec['type']}, not a {expect_type}")
    inst = _instances.get(name)
    if inst is not None:
        return inst
    with _instances_lock:
        inst = _instances.get(name)
        if inst is None:
            if spec["type"] == "counter":
                inst = _metrics.Counter(name, spec["description"],
                                        tag_keys=spec["tag_keys"])
            elif spec["type"] == "gauge":
                inst = _metrics.Gauge(name, spec["description"],
                                      tag_keys=spec["tag_keys"])
            else:
                inst = _metrics.Histogram(name, spec["description"],
                                          boundaries=spec.get("boundaries"),
                                          tag_keys=spec["tag_keys"])
            _instances[name] = inst
    return inst


def counter(name: str) -> _metrics.Counter:
    return _get(name, "counter")  # type: ignore[return-value]


def gauge(name: str) -> _metrics.Gauge:
    return _get(name, "gauge")  # type: ignore[return-value]


def histogram(name: str) -> _metrics.Histogram:
    return _get(name, "histogram")  # type: ignore[return-value]


# Exception-safe record helpers: telemetry is never allowed to fail the
# instrumented path (e.g. a user metric squatting on a catalog name makes
# instantiation raise), so framework call sites use these instead of
# hand-rolling try/except around every counter/gauge/histogram call.

def inc(name: str, value: float = 1.0,
        tags: Optional[Dict[str, str]] = None) -> None:
    try:
        counter(name).inc(value, tags=tags)
    except Exception:
        pass


def observe(name: str, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
    try:
        histogram(name).observe(value, tags=tags)
    except Exception:
        pass


def observe_many(name: str, values, tags: Optional[Dict[str, str]] = None
                 ) -> None:
    """Batch-observe under one lock (amortized publishers: stage-wait
    folds, the scheduler's attempt-sample flush)."""
    try:
        histogram(name).observe_many(values, tags=tags)
    except Exception:
        pass


def set_gauge(name: str, value: float,
              tags: Optional[Dict[str, str]] = None) -> None:
    try:
        gauge(name).set(value, tags=tags)
    except Exception:
        pass


def note_swallowed(where: str, exc: Optional[BaseException] = None) -> None:
    """Account for an intentionally swallowed control-plane exception.

    The RT202 lint rule forbids bare ``except Exception: pass`` in
    control-plane modules: a swallowed error must at least leave a
    debug-log line and bump ``ray_tpu_internal_swallowed_errors_total``
    so a misbehaving subsystem shows up on the scrape instead of only in
    a postmortem."""
    inc("ray_tpu_internal_swallowed_errors_total", tags={"where": where})
    try:
        import logging
        logging.getLogger("ray_tpu").debug(
            "swallowed error in %s: %r", where, exc)
    except Exception:
        pass


def _reset_for_tests() -> None:
    """Drop cached instances (called from metrics._reset_for_tests: the
    registry they were registered in is being cleared, and a stale cached
    instance would record into an orphaned state dict)."""
    global _goodput_latest
    with _instances_lock:
        _instances.clear()
    _goodput_latest = None


# -- profile spans ---------------------------------------------------------


def _emit_span(name: str, category: str, start_s: float, end_s: float,
               extra: Optional[Dict[str, Any]] = None) -> None:
    """Record one finished span into the driver's timeline buffer.

    Driver: direct append.  Worker: fire-and-forget control frame (request
    id 0 is never in the pending-reply table, so the head's reply is
    dropped harmlessly) — no round-trip on hot paths.  No runtime: no-op.
    """
    from ray_tpu._private import runtime as rtmod
    rt = rtmod.current_runtime()
    if rt is None:
        return
    pid = category
    # One timeline row per THREAD, not per process: concurrent spans from
    # different threads on a shared row would interleave and break the
    # viewer's nesting of same-thread parent/child spans.
    tid = f"pid:{os.getpid()}:t{threading.get_ident() % 100000}"
    try:
        if hasattr(rt, "ctl_add_profile_span"):
            rt.ctl_add_profile_span(name, category, start_s, end_s,
                                    pid, tid, extra)
        elif hasattr(rt, "send") and hasattr(rt, "worker_id"):
            from ray_tpu._private.protocol import RpcCall
            rt.send(RpcCall(0, rt.worker_id, "add_profile_span",
                            (name, category, start_s, end_s, pid, tid,
                             extra), {}))
        elif hasattr(rt, "control"):
            rt.control("add_profile_span", name, category, start_s, end_s,
                       pid, tid, extra)
    except Exception:
        pass  # telemetry is never allowed to fail the instrumented path


# Per-thread open-span stack: gives nested profile_spans parent linkage
# and lets a parent subtract its children's time (``self_s``), so an
# inner span's duration is never silently attributed to both levels.
# Shared by telemetry.profile_span and util.state.profile_span.
_span_tls = threading.local()
_span_seq_lock = threading.Lock()
_span_seq = 0


def _next_span_id() -> int:
    global _span_seq
    with _span_seq_lock:
        _span_seq += 1
        return _span_seq


def _span_stack() -> list:
    stack = getattr(_span_tls, "stack", None)
    if stack is None:
        stack = _span_tls.stack = []
    return stack


def _span_enter(entry: Dict[str, Any]) -> Dict[str, Any]:
    """Push one open-span frame; returns it annotated with its id and
    its parent's id (None at the top level)."""
    stack = _span_stack()
    entry["span_id"] = _next_span_id()
    entry["parent_id"] = stack[-1]["span_id"] if stack else None
    entry["child_s"] = 0.0
    stack.append(entry)
    return entry


def _span_exit(entry: Dict[str, Any], dur_s: float) -> Dict[str, Any]:
    """Pop a frame (tolerating mismatched exits), charge the duration to
    the parent's child time, and return linkage extras for the span:
    span_id/parent_id plus ``self_s`` — the duration EXCLUSIVE of nested
    spans, which is what nesting used to misattribute."""
    stack = _span_stack()
    if entry in stack:
        # Normal case pops the top; an out-of-order exit (generator
        # suspension etc.) drops everything above it rather than
        # corrupting later pairings.
        del stack[stack.index(entry):]
    if stack:
        stack[-1]["child_s"] += dur_s
    return {"span_id": entry["span_id"],
            "parent_id": entry["parent_id"],
            "self_s": max(0.0, dur_s - entry["child_s"])}


class profile_span:
    """Cheap system-span context manager for framework hot paths.

    Unlike ``util.state.profile_span`` (the user API, which requires a
    runtime and does a blocking control call), this one no-ops without a
    runtime and never waits on a reply — safe inside the engine decode
    loop or a bench process that never called ``ray_tpu.init()``.

    Re-entrant and nesting-aware: a span opened inside another span is
    linked to its parent (``extra["parent_id"]``) and the parent's
    ``extra["self_s"]`` excludes nested time, so inner durations are
    attributed exactly once.  One instance may be entered recursively
    (per-entry state lives on a stack, not the instance).
    """

    __slots__ = ("name", "category", "extra", "_frames")

    def __init__(self, name: str, category: str = "system",
                 extra: Optional[Dict[str, Any]] = None):
        self.name = name
        self.category = category
        self.extra = extra
        self._frames: list = []

    def __enter__(self) -> "profile_span":
        # Wall clock positions the span; monotonic measures its length so
        # an NTP step mid-span can't yield a negative/garbage duration.
        entry = _span_enter({"start": time.time(),
                             "start_mono": time.monotonic()})
        self._frames.append(entry)
        return self

    def __exit__(self, *exc) -> bool:
        entry = self._frames.pop()
        dur = time.monotonic() - entry["start_mono"]
        extra = dict(self.extra or {})
        extra.update(_span_exit(entry, dur))
        _emit_span(self.name, self.category, entry["start"],
                   entry["start"] + dur, extra)
        return False


# -- goodput accounting ----------------------------------------------------

_goodput_latest: Optional["GoodputTracker"] = None

# Checkpoint seconds accrued in THIS process since the last report():
# save_pytree notes them, train._context.report() pops them into the
# report payload, and the driver-side GoodputTracker reattributes that
# slice of the observed "step" window to the "checkpoint" phase.
_pending_ckpt_lock = threading.Lock()
_pending_ckpt_s = 0.0


def note_checkpoint_seconds(seconds: float) -> None:
    global _pending_ckpt_s
    if seconds > 0:
        with _pending_ckpt_lock:
            _pending_ckpt_s += seconds


def pop_checkpoint_seconds() -> float:
    global _pending_ckpt_s
    with _pending_ckpt_lock:
        s, _pending_ckpt_s = _pending_ckpt_s, 0.0
    return s


class GoodputTracker:
    """Partitions wall time into named phases; goodput = productive/total.

    The productive phase is ``"step"``; everything else (init, restart,
    checkpoint, idle, ...) is overhead.  ``enter(phase)`` switches phase;
    ``reattribute(phase, seconds)`` moves already-elapsed seconds out of
    the current phase (used for worker-reported checkpoint time that
    happened inside a driver-observed "step" window).  Each transition
    refreshes the ``ray_tpu_train_goodput_ratio`` gauge, so the scrape
    endpoint shows live goodput mid-run (MegaScale-style accounting:
    at 10k-chip scale the difference between 0.95 and 0.85 is a
    thousand wasted chips)."""

    PRODUCTIVE = "step"

    def __init__(self, initial_phase: str = "init",
                 update_gauge: bool = True):
        global _goodput_latest
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._phase = initial_phase
        self._since = self._t0
        self._finished = False
        self.seconds: Dict[str, float] = {}
        self._update_gauge = update_gauge
        _goodput_latest = self

    def _accumulate_locked(self, now: float) -> None:
        dt = max(0.0, now - self._since)
        self.seconds[self._phase] = self.seconds.get(self._phase, 0.0) + dt
        self._since = now

    def enter(self, phase: str) -> None:
        with self._lock:
            if self._finished:
                return
            now = time.monotonic()
            self._accumulate_locked(now)
            self._phase = phase
        self._refresh_gauge()

    def reattribute(self, phase: str, seconds: float) -> None:
        """Move ``seconds`` of already-elapsed current-phase time into
        ``phase`` (clamped to what the current phase has actually
        accrued, including the open interval)."""
        if seconds <= 0:
            return
        with self._lock:
            # Same-phase check under the lock: a concurrent enter() can
            # swap _phase between a bare check and the accounting below.
            if self._finished or phase == self._phase:
                return
            self._accumulate_locked(time.monotonic())
            avail = self.seconds.get(self._phase, 0.0)
            moved = min(seconds, avail)
            self.seconds[self._phase] = avail - moved
            self.seconds[phase] = self.seconds.get(phase, 0.0) + moved
        self._refresh_gauge()

    def finish(self) -> Dict[str, Any]:
        with self._lock:
            if not self._finished:
                self._accumulate_locked(time.monotonic())
                self._finished = True
        self._refresh_gauge()
        return self.summary()

    def ratio(self) -> float:
        with self._lock:
            now = time.monotonic()
            open_dt = 0.0 if self._finished else max(0.0, now - self._since)
            total = sum(self.seconds.values()) + open_dt
            productive = self.seconds.get(self.PRODUCTIVE, 0.0) + (
                open_dt if self._phase == self.PRODUCTIVE else 0.0)
        if total <= 0:
            return 0.0
        return productive / total

    def _refresh_gauge(self) -> None:
        if self._update_gauge:
            set_gauge("ray_tpu_train_goodput_ratio", self.ratio())

    def summary(self) -> Dict[str, Any]:
        r = self.ratio()
        with self._lock:
            phases = dict(self.seconds)
            if not self._finished:
                phases[self._phase] = phases.get(self._phase, 0.0) + max(
                    0.0, time.monotonic() - self._since)
        total = sum(phases.values())
        return {
            "goodput_ratio": r,
            "total_s": total,
            "productive_s": phases.get(self.PRODUCTIVE, 0.0),
            "phases_s": phases,
        }


def goodput_summary() -> Optional[Dict[str, Any]]:
    """The most recent GoodputTracker's summary (None before any run)."""
    return _goodput_latest.summary() if _goodput_latest is not None else None


# -- dashboard summary -----------------------------------------------------


def summary() -> Dict[str, Any]:
    """Cluster-merged built-in metrics grouped by subsystem, for
    ``GET /api/metrics/summary``.  Counters/gauges flatten to scalar
    samples; histograms report count/sum/mean per tag set."""
    by_name, acc = _metrics._aggregate_snapshots()
    subsystems: Dict[str, Dict[str, Any]] = {}
    for name, spec in CATALOG.items():
        subsystem = name.split("_")[2]  # ray_tpu_<subsystem>_...
        if spec["type"] == "histogram":
            sums = acc.get(name + "_sum", {})
            counts = acc.get(name + "_count", {})
            samples = []
            for key, (tags, total) in sorted(sums.items()):
                n = counts.get(key, (tags, 0.0))[1]
                samples.append({"tags": tags, "count": n, "sum": total,
                                "mean": (total / n) if n else 0.0})
        else:
            samples = [{"tags": tags, "value": v}
                       for _k, (tags, v) in sorted(acc.get(name, {}).items())]
        if not samples:
            continue
        subsystems.setdefault(subsystem, {})[name] = {
            "type": spec["type"], "description": spec["description"],
            "samples": samples}
    return {"subsystems": subsystems, "goodput": goodput_summary()}
