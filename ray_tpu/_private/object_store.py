"""Host shared-memory object store (plasma equivalent).

The reference's plasma store (reference: src/ray/object_manager/plasma/
store.h:55 PlasmaStore, eviction_policy.cc LRU, dlmalloc.cc shm arena) holds
immutable sealed objects in shared memory for zero-copy reads by co-located
workers, with LRU eviction and disk spill (reference:
src/ray/raylet/local_object_manager.h:46 SpillObjects/restore).

TPU-native differences: objects here are the *host-side* staging tier — large
numpy/jax host arrays serialized with out-of-band buffers land in a shm
segment and deserialize as zero-copy views, from which ``jax.device_put``
moves them HBM-ward.  Device-to-device movement never goes through this store
(it rides ICI via XLA collectives); this store serves task args/returns,
dataset blocks, and checkpoint staging.

Implementation: one POSIX shm segment per object (named ``rt_<id16>``), a
store index in the owning node process, LRU eviction to a spill directory when
over the configured cap.  Any process on the host can map a sealed object by
name without talking to the store (the directory hands out the name).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, Optional, Tuple

from ray_tpu.storeview import events as _sv

from . import serialization
from .config import Config
from .ids import ObjectID

#: default spill root swept for orphans (dirs named <pid>/arena_<pid>).
SPILL_ROOT = os.path.join("/tmp", "ray_tpu_spill")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # exists but not ours (EPERM) — treat as alive
    return True


def _dir_nbytes(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for fname in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, fname))
            except OSError:
                pass
    return total


def sweep_orphan_spills(root: Optional[str] = None) -> int:
    """Delete spill directories left by dead store processes.

    Spill files live under ``SPILL_ROOT/<pid>`` (Python store) or
    ``SPILL_ROOT/arena_<pid>`` (native arena); a SIGKILLed node leaves
    them behind forever.  Sweeps only dirs whose embedded pid is dead,
    so concurrent live stores on the host are never touched.  Returns
    reclaimed bytes (also published as
    ``ray_tpu_store_spill_reclaimed_bytes_total``).
    """
    root = root or SPILL_ROOT
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    reclaimed = 0
    for name in names:
        pid_s = name[6:] if name.startswith("arena_") else name
        try:
            pid = int(pid_s)
        except ValueError:
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(root, name)
        reclaimed += _dir_nbytes(path)
        shutil.rmtree(path, ignore_errors=True)
    if reclaimed:
        from ray_tpu.util import telemetry
        telemetry.inc("ray_tpu_store_spill_reclaimed_bytes_total",
                      reclaimed)
    return reclaimed


_boot_sweep_done = False


def _maybe_boot_sweep() -> None:
    """Once-per-process orphan sweep, run from store construction (the
    "next boot" half of spill-file GC; the shutdown half is each store's
    own-dir cleanup)."""
    global _boot_sweep_done
    if _boot_sweep_done:
        return
    _boot_sweep_done = True
    try:
        sweep_orphan_spills()
    except Exception as e:  # GC must never fail store construction
        from ray_tpu.util import telemetry
        telemetry.note_swallowed("object_store.boot_sweep", e)


def _shm_name(object_id: ObjectID) -> str:
    # Full 22-byte hex (44 chars): truncating would collide ObjectIDs that
    # differ only in the trailing return-index bytes.
    return "rt_" + object_id.hex()


class _SafeSharedMemory(shared_memory.SharedMemory):
    """SharedMemory whose close() tolerates live exported views.

    Zero-copy reads hand out numpy views over the mapping; at interpreter
    exit those views can outlive the segment object, and mmap.close() raises
    BufferError.  The segment is reclaimed at process exit either way.
    """

    def close(self) -> None:  # noqa: D102
        try:
            super().close()
        except BufferError:
            pass


def _open_untracked(name: str, create: bool, size: int = 0) -> shared_memory.SharedMemory:
    """SharedMemory without the resource_tracker auto-unlink.

    Python's resource tracker unlinks segments when any attaching process
    exits; objects here outlive their creating worker by design, so the store
    owns unlink explicitly.
    """
    shm = _SafeSharedMemory(name=name, create=create, size=size)
    # Python <=3.12 registers on attach too, so always unregister.
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    return shm


@dataclass
class _Entry:
    nbytes: int
    sealed: bool = False
    pinned: int = 0
    shm: Optional[shared_memory.SharedMemory] = None
    spilled_path: Optional[str] = None
    create_time: float = field(default_factory=time.monotonic)


class ObjectStoreFullError(Exception):
    pass


class SharedMemoryStore:
    """Node-local store of immutable shared-memory objects."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self._capacity = capacity_bytes or Config.get("object_store_memory")
        self._spill_dir = spill_dir or Config.get("object_spill_dir") or None
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()
        self._used = 0
        self._lock = threading.RLock()
        self.num_spilled = 0
        self.num_restored = 0
        self.num_evictions = 0  # Python store spills, never drops: stays 0
        # Lifecycle ring (storeview): every mutation below records one
        # event when tracing is on; `ray-tpu obj why` and the memory
        # summary read it.
        self.view = _sv.StoreEventRing()
        _maybe_boot_sweep()

    # -- write path ---------------------------------------------------------

    def create(self, object_id: ObjectID, nbytes: int) -> memoryview:
        with self._lock:
            if object_id in self._entries:
                raise ValueError(f"object {object_id} already exists")
            self._ensure_space(nbytes)
            shm = _open_untracked(_shm_name(object_id), create=True,
                                  size=max(nbytes, 1))
            self._entries[object_id] = _Entry(nbytes=nbytes, shm=shm)
            self._used += nbytes
            if _sv.enabled():
                self.view.push(_sv.E_CREATE, object_id.binary(), nbytes)
            return shm.buf[:nbytes]

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            self._entries[object_id].sealed = True
        if _sv.enabled():
            self.view.push(_sv.E_SEAL, object_id.binary())

    def put_serialized(self, object_id: ObjectID, meta: bytes, buffers) -> int:
        nbytes = serialization.payload_nbytes(meta, buffers)
        view = self.create(object_id, nbytes)
        serialization.write_payload_into(view, meta, buffers)
        del view
        self.seal(object_id)
        return nbytes

    def put(self, object_id: ObjectID, value: Any) -> int:
        meta, buffers = serialization.serialize_payload(value)
        return self.put_serialized(object_id, meta, buffers)

    # -- read path ----------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get_buffer(self, object_id: ObjectID) -> Tuple[memoryview, Any]:
        """Returns (payload view, keepalive handle). Restores from spill."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                raise KeyError(f"object {object_id} not in store")
            if not e.sealed:
                raise ValueError(f"object {object_id} not sealed")
            if e.shm is None:
                self._restore(object_id, e)
            self._entries.move_to_end(object_id)  # LRU touch
            if _sv.enabled():
                self.view.push(_sv.E_GET, object_id.binary(), e.nbytes)
            return e.shm.buf[: e.nbytes], e.shm

    def get(self, object_id: ObjectID) -> Any:
        buf, _keepalive = self.get_buffer(object_id)
        return serialization.read_payload_from(buf)

    def pin(self, object_id: ObjectID,
            pinner: Optional[str] = None) -> None:
        with self._lock:
            self._entries[object_id].pinned += 1
        if _sv.enabled():
            self.view.push(_sv.E_PIN, object_id.binary(), detail=pinner)

    def unpin(self, object_id: ObjectID,
              pinner: Optional[str] = None) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.pinned <= 0:
                return
            e.pinned -= 1
        if _sv.enabled():
            self.view.push(_sv.E_UNPIN, object_id.binary(), detail=pinner)

    def try_pin(self, object_id: ObjectID,
                pinner: Optional[str] = None) -> bool:
        """Pin if the store owns this object (emergency-replica staging:
        a pinned snapshot is exempt from LRU spill/eviction).  Objects
        created by worker processes live in their own segments outside
        this index; those return False and rely on the runtime's
        escape-mark instead."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return False
            e.pinned += 1
        if _sv.enabled():
            self.view.push(_sv.E_PIN, object_id.binary(), detail=pinner)
        return True

    def try_unpin(self, object_id: ObjectID,
                  pinner: Optional[str] = None) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.pinned <= 0:
                return False
            e.pinned -= 1
        if _sv.enabled():
            self.view.push(_sv.E_UNPIN, object_id.binary(), detail=pinner)
        return True

    def num_pinned(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values() if e.pinned > 0)

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            if e.shm is not None:
                self._used -= e.nbytes
                try:
                    e.shm.close()
                    e.shm.unlink()
                except FileNotFoundError:
                    pass
            if e.spilled_path and os.path.exists(e.spilled_path):
                os.unlink(e.spilled_path)
        if _sv.enabled():
            self.view.push(_sv.E_DELETE, object_id.binary(), e.nbytes)

    def shm_name(self, object_id: ObjectID) -> str:
        return _shm_name(object_id)

    def descriptor(self, object_id: ObjectID) -> Optional[tuple]:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                return None
            return ("shm", _shm_name(object_id), e.nbytes)

    # -- cross-node transfer (raw payload bytes) ----------------------------

    def read_raw_by_key(self, key: bytes) -> Optional[bytes]:
        """Copy out the serialized payload (for push to another node)."""
        try:
            buf, _keep = self.get_buffer(ObjectID(key))
        except (KeyError, ValueError):
            return None
        return bytes(buf)

    def put_raw(self, object_id: ObjectID, payload: bytes) -> Optional[tuple]:
        """Store a payload pulled from another node; returns the local
        descriptor (existing one if the object already landed here), or
        None when the store can't hold it."""
        try:
            view = self.create(object_id, len(payload))
        except ValueError:
            return self.descriptor(object_id)
        except ObjectStoreFullError:
            return None
        except FileExistsError:
            # The producer lives on this host: its segment already
            # carries this payload (ids are globally unique, payloads
            # immutable), and shm names are host-global.  Point the
            # caller at the live segment instead of caching a copy
            # under a name we cannot create.
            return ("shm", _shm_name(object_id), len(payload))
        view[:] = payload
        del view
        self.seal(object_id)
        return self.descriptor(object_id)

    def stats(self) -> Dict[str, int]:
        # Same keys as NativeArenaStore.stats() (native=0|1 tells them
        # apart) so the memory summary renders identically for both.
        with self._lock:
            in_mem = pinned = pinned_bytes = spilled_bytes = 0
            for e in self._entries.values():
                if e.shm is not None:
                    in_mem += 1
                else:
                    spilled_bytes += e.nbytes
                if e.pinned > 0:
                    pinned += 1
                    pinned_bytes += e.nbytes
            return {"num_objects": len(self._entries),
                    "used_bytes": self._used,
                    "capacity_bytes": self._capacity,
                    "pinned_bytes": pinned_bytes,
                    "spilled_bytes": spilled_bytes,
                    "num_spilled": self.num_spilled,
                    "num_restored": self.num_restored,
                    "num_evictions": self.num_evictions,
                    "num_in_memory": in_mem,
                    "num_pinned": pinned,
                    "native": 0}

    def shutdown(self) -> None:
        with self._lock:
            for oid in list(self._entries):
                self.delete(oid)
        # Shutdown half of spill-file GC: per-object deletes above remove
        # tracked spill files; anything left in our default spill dir is
        # an orphan (crashed mid-spill, or an untracked leftover).
        if not self._spill_dir:
            own = os.path.join(SPILL_ROOT, str(os.getpid()))
            leftover = _dir_nbytes(own)
            shutil.rmtree(own, ignore_errors=True)
            if leftover:
                from ray_tpu.util import telemetry
                telemetry.inc("ray_tpu_store_spill_reclaimed_bytes_total",
                              leftover)

    # -- eviction / spill ---------------------------------------------------

    def _ensure_space(self, nbytes: int) -> None:
        if self._used + nbytes <= self._capacity:
            return
        # Evict sealed, unpinned, in-memory objects in LRU order.
        for oid, e in list(self._entries.items()):
            if self._used + nbytes <= self._capacity:
                break
            if e.sealed and e.pinned == 0 and e.shm is not None:
                self._spill(oid, e)
        if self._used + nbytes > self._capacity:
            raise ObjectStoreFullError(
                f"need {nbytes} bytes; {self._used}/{self._capacity} used and "
                "nothing evictable" + self._pinned_detail())

    def _pinned_detail(self, top_n: int = 3) -> str:
        """Actionable tail for ObjectStoreFullError: the largest pinned
        objects and who pinned them (from the lifecycle ring)."""
        try:
            pinned = sorted(
                ((oid, e) for oid, e in self._entries.items()
                 if e.pinned > 0),
                key=lambda kv: kv[1].nbytes, reverse=True)[:top_n]
            if not pinned:
                return ""
            parts = []
            for oid, e in pinned:
                who = ",".join(self.view.pinners_of(oid.binary())) or "?"
                parts.append(f"{oid.hex()[:12]} "
                             f"({e.nbytes}B pins={e.pinned} by {who})")
            return "; top pinned: " + ", ".join(parts)
        except Exception:  # noqa: BLE001 — error enrichment is display-only
            return ""

    def _spill_path(self, object_id: ObjectID) -> str:
        d = self._spill_dir
        if not d:
            d = os.path.join("/tmp", "ray_tpu_spill", str(os.getpid()))
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, object_id.hex())

    def _spill(self, object_id: ObjectID, e: _Entry) -> None:
        path = self._spill_path(object_id)
        with open(path, "wb") as f:
            f.write(e.shm.buf[: e.nbytes])
        e.spilled_path = path
        e.shm.close()
        e.shm.unlink()
        e.shm = None
        self._used -= e.nbytes
        self.num_spilled += 1
        if _sv.enabled():
            self.view.push(_sv.E_SPILL, object_id.binary(), e.nbytes)

    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        if not e.spilled_path:
            raise KeyError(f"object {object_id} has no data and no spill copy")
        self._ensure_space(e.nbytes)
        shm = _open_untracked(_shm_name(object_id), create=True,
                              size=max(e.nbytes, 1))
        with open(e.spilled_path, "rb") as f:
            f.readinto(shm.buf)
        e.shm = shm
        self._used += e.nbytes
        self.num_restored += 1
        if _sv.enabled():
            self.view.push(_sv.E_RESTORE, object_id.binary(), e.nbytes)


class NativeArenaStore:
    """ctypes wrapper over the C++ arena store (ray_tpu/_native/store.cc).

    One shm arena per node process; best-fit allocation, LRU spill/restore and
    plasma-style pinning live in C++.  This class adds the python-side mapping
    for zero-copy reads/writes from the owner process and the payload codec.
    Descriptors are ("shma", segment, offset, nbytes, id_bytes); offsets are
    only valid while the object is pinned, so hand-outs must go through
    ``pin_desc_by_key`` (which refreshes the offset under the store lock).
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        from .. import _native
        lib = _native.load_store_library()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self._lib = lib
        capacity = capacity_bytes or Config.get("object_store_memory")
        spill = spill_dir or Config.get("object_spill_dir") or os.path.join(
            "/tmp", "ray_tpu_spill", f"arena_{os.getpid()}")
        name = f"rta_{os.getpid()}_{os.urandom(4).hex()}"
        self._h = lib.rts_create(name.encode(), capacity, spill.encode())
        if not self._h:
            raise RuntimeError("native store arena creation failed")
        self.segment_name = name
        self._spill_dir = spill
        self._shm = _open_untracked(name, create=False)
        self._closed = False
        # Guards stats() vs shutdown(): a diagnostics/death-bundle
        # thread reading stats concurrent with rts_destroy is a native
        # use-after-free (segfault, not an exception).  Data-path calls
        # don't take this — the C++ store locks internally and the node
        # stops dispatching before it shuts its store down; only the
        # postmortem reader crosses that line.
        self._life = threading.Lock()
        # Lifecycle ring (storeview): spill/evict decisions happen inside
        # the C++ LRU so those arrive as stats-diff counters only; every
        # Python-visible mutation records an event here.
        self.view = _sv.StoreEventRing()
        _maybe_boot_sweep()

    # -- write path ---------------------------------------------------------

    def allocate(self, object_id: ObjectID, nbytes: int) -> int:
        off = self._lib.rts_allocate(self._h, object_id.binary(),
                                     len(object_id.binary()), nbytes)
        if off == -2:
            raise ValueError(f"object {object_id} already exists")
        if off < 0:
            raise ObjectStoreFullError(
                f"arena cannot fit {nbytes} bytes (all pinned or unsealed)"
                + self._pinned_detail())
        if _sv.enabled():
            self.view.push(_sv.E_CREATE, object_id.binary(), nbytes)
        return off

    def _pinned_detail(self, top_n: int = 3) -> str:
        """Actionable tail for ObjectStoreFullError, from the lifecycle
        ring (the C++ index has no pinner attribution)."""
        try:
            pinned = self.view.top_pinned(top_n)
            if not pinned:
                return ""
            parts = [f"{p['object_id'][:12]} ({p['nbytes']}B "
                     f"pins={p['pins']} by "
                     f"{','.join(p['pinners']) or '?'})" for p in pinned]
            return "; top pinned: " + ", ".join(parts)
        except Exception:  # noqa: BLE001 — error enrichment is display-only
            return ""

    def seal(self, object_id: ObjectID) -> None:
        self._lib.rts_seal(self._h, object_id.binary(),
                           len(object_id.binary()))
        if _sv.enabled():
            self.view.push(_sv.E_SEAL, object_id.binary())

    def put_serialized(self, object_id: ObjectID, meta: bytes, buffers) -> int:
        nbytes = serialization.payload_nbytes(meta, buffers)
        off = self.allocate(object_id, nbytes)
        serialization.write_payload_into(
            self._shm.buf[off: off + nbytes], meta, buffers)
        self.seal(object_id)
        return nbytes

    def put(self, object_id: ObjectID, value: Any) -> int:
        meta, buffers = serialization.serialize_payload(value)
        return self.put_serialized(object_id, meta, buffers)

    def allocate_for_worker(self, object_id: ObjectID,
                            nbytes: int) -> Optional[Tuple[str, int]]:
        """Grant an arena slot to a worker process (plasma Create RPC)."""
        try:
            off = self.allocate(object_id, nbytes)
        except (ObjectStoreFullError, ValueError):
            return None
        return self.segment_name, off

    # -- read path ----------------------------------------------------------

    def _lookup(self, key: bytes, pin: bool) -> Optional[Tuple[int, int]]:
        import ctypes
        off = ctypes.c_uint64()
        n = ctypes.c_uint64()
        rc = self._lib.rts_lookup_pin(self._h, key, len(key), 1 if pin else 0,
                                      ctypes.byref(off), ctypes.byref(n))
        if rc != 0:
            return None
        return off.value, n.value

    def contains(self, object_id: ObjectID) -> bool:
        key = object_id.binary()
        return bool(self._lib.rts_contains(self._h, key, len(key)))

    def descriptor(self, object_id: ObjectID) -> Optional[tuple]:
        """Unpinned descriptor snapshot (for the object directory); consumers
        must refresh through pin_desc_by_key before dereferencing."""
        key = object_id.binary()
        res = self._lookup(key, pin=False)
        if res is None:
            return None
        return ("shma", self.segment_name, res[0], res[1], key)

    def pin_desc_by_key(self, key: bytes,
                        pinner: Optional[str] = None) -> Optional[tuple]:
        res = self._lookup(key, pin=True)
        if res is None:
            return None
        if _sv.enabled():
            self.view.push(_sv.E_PIN, key, res[1], detail=pinner)
        return ("shma", self.segment_name, res[0], res[1], key)

    def unpin_key(self, key: bytes,
                  pinner: Optional[str] = None) -> None:
        self._lib.rts_unpin(self._h, key, len(key))
        if _sv.enabled():
            self.view.push(_sv.E_UNPIN, key, detail=pinner)

    def read_by_key(self, key: bytes, pin: bool) -> Optional[Any]:
        """Owner-process zero-copy read (views into the arena mapping)."""
        res = self._lookup(key, pin=pin)
        if res is None:
            return None
        off, nbytes = res
        if _sv.enabled():
            self.view.push(_sv.E_GET, key, nbytes)
            if pin:
                self.view.push(_sv.E_PIN, key, nbytes)
        return serialization.read_payload_from(self._shm.buf[off: off + nbytes])

    # -- cross-node transfer (raw payload bytes) ----------------------------

    def read_raw_by_key(self, key: bytes) -> Optional[bytes]:
        """Copy out the serialized payload (pin across the copy so a
        concurrent eviction can't move the offset under us)."""
        res = self._lookup(key, pin=True)
        if res is None:
            return None
        try:
            off, nbytes = res
            if _sv.enabled():
                self.view.push(_sv.E_GET, key, nbytes)
            return bytes(self._shm.buf[off: off + nbytes])
        finally:
            # Transient copy pin, not a reader pin: skip the ring events.
            self._lib.rts_unpin(self._h, key, len(key))

    def put_raw(self, object_id: ObjectID, payload: bytes) -> Optional[tuple]:
        """Store a payload pulled from another node; returns the local
        descriptor (existing one if the object already landed here), or
        None when the arena can't hold it."""
        try:
            off = self.allocate(object_id, len(payload))
        except ValueError:
            return self.descriptor(object_id)
        except ObjectStoreFullError:
            return None
        self._shm.buf[off: off + len(payload)] = payload
        self.seal(object_id)
        return self.descriptor(object_id)

    def get(self, object_id: ObjectID) -> Any:
        value = self.read_by_key(object_id.binary(), pin=False)
        if value is None:
            raise KeyError(f"object {object_id} not in store")
        return value

    def pin(self, object_id: ObjectID,
            pinner: Optional[str] = None) -> None:
        key = object_id.binary()
        if self._lookup(key, pin=True) is not None and _sv.enabled():
            self.view.push(_sv.E_PIN, key, detail=pinner)

    def unpin(self, object_id: ObjectID,
              pinner: Optional[str] = None) -> None:
        self.unpin_key(object_id.binary(), pinner=pinner)

    def try_pin(self, object_id: ObjectID,
                pinner: Optional[str] = None) -> bool:
        """Arena-store counterpart of SharedMemoryStore.try_pin (the
        emergency-replica pin API): pin when present, report whether the
        arena actually holds the object."""
        key = object_id.binary()
        if self._lookup(key, pin=True) is None:
            return False
        if _sv.enabled():
            self.view.push(_sv.E_PIN, key, detail=pinner)
        return True

    def try_unpin(self, object_id: ObjectID,
                  pinner: Optional[str] = None) -> bool:
        if not self.contains(object_id):
            return False
        self.unpin_key(object_id.binary(), pinner=pinner)
        return True

    def delete(self, object_id: ObjectID) -> None:
        key = object_id.binary()
        if self._lib.rts_delete(self._h, key, len(key)) != 0:
            raise KeyError(f"object {object_id} not in store")
        if _sv.enabled():
            self.view.push(_sv.E_DELETE, key)

    def stats(self) -> Dict[str, int]:
        # Same keys as SharedMemoryStore.stats(); values come from the
        # C++ index in one call (store.cc rts_stats).
        import ctypes
        out = (ctypes.c_uint64 * 10)()
        with self._life:
            if not self._closed:
                self._lib.rts_stats(self._h, ctypes.byref(out))
        return {"num_objects": int(out[0]), "used_bytes": int(out[1]),
                "capacity_bytes": int(out[2]),
                "pinned_bytes": int(out[8]),
                "spilled_bytes": int(out[9]),
                "num_spilled": int(out[3]),
                "num_restored": int(out[4]), "num_evictions": int(out[5]),
                "num_in_memory": int(out[6]), "num_pinned": int(out[7]),
                "native": 1}

    def shutdown(self) -> None:
        # _life serializes the close flag against stats(): once we hold
        # the lock no stats call is mid-rts_stats, and every later one
        # sees _closed and skips the native call — so destroying the
        # handle below cannot race a reader.  _h itself stays set (all
        # its accesses are the data path's, which ends before shutdown).
        with self._life:
            if self._closed:
                return
            self._closed = True
        try:
            self._shm.close()
        except Exception:
            pass
        self._lib.rts_destroy(self._h)  # removes tracked spill files
        # Shutdown half of spill-file GC: anything left in our spill dir
        # after rts_destroy is an orphan (crashed mid-spill).
        if self._spill_dir.startswith(SPILL_ROOT):
            leftover = _dir_nbytes(self._spill_dir)
            shutil.rmtree(self._spill_dir, ignore_errors=True)
            if leftover:
                from ray_tpu.util import telemetry
                telemetry.inc("ray_tpu_store_spill_reclaimed_bytes_total",
                              leftover)


def create_store(capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
    """Node store factory: native C++ arena when buildable, else Python."""
    if Config.get("use_native_store"):
        try:
            return NativeArenaStore(capacity_bytes, spill_dir)
        except Exception as e:
            import logging
            logging.getLogger("ray_tpu").warning(
                "native arena store unavailable (%s); falling back to the "
                "Python per-segment store", e)
    return SharedMemoryStore(capacity_bytes, spill_dir)


class ArenaReader:
    """Maps arena segments by name in non-owner processes (one mapping per
    segment, cached for the process lifetime)."""

    _maps: Dict[str, shared_memory.SharedMemory] = {}
    _lock = threading.Lock()

    @classmethod
    def mapping(cls, segment: str) -> shared_memory.SharedMemory:
        with cls._lock:
            shm = cls._maps.get(segment)
            if shm is None:
                shm = _open_untracked(segment, create=False)
                cls._maps[segment] = shm
            return shm

    @classmethod
    def read(cls, desc) -> Tuple[Any, Any]:
        _, segment, off, nbytes = desc[0], desc[1], desc[2], desc[3]
        shm = cls.mapping(segment)
        value = serialization.read_payload_from(shm.buf[off: off + nbytes])
        return value, shm

    @classmethod
    def write(cls, segment: str, off: int, meta: bytes, buffers) -> int:
        shm = cls.mapping(segment)
        nbytes = serialization.payload_nbytes(meta, buffers)
        serialization.write_payload_into(
            shm.buf[off: off + nbytes], meta, buffers)
        return nbytes


# -- page-blob export/import (disaggregated LLM serving) --------------------


def export_page_blob(store, object_id: ObjectID, value: Any) -> Optional[tuple]:
    """Publish a prefill KV page blob as a sealed, PINNED store object
    and return its descriptor for same-host zero-copy import (the
    disagg prefill->decode handoff path).  The pin holds it exempt from
    LRU spill/eviction for the export->import window — an unpinned
    descriptor could be unlinked (Python store) or have its arena
    offset reused (native store) before the decode worker maps it.
    Balance with :func:`release_page_blob` after import.  Returns None
    when the store can't hold it — the caller falls back to direct
    in-process handoff; the blob is never silently dropped."""
    try:
        store.put(object_id, value)
    except ValueError:
        pass                      # already exported (idempotent republish)
    except ObjectStoreFullError:
        return None
    if not store.try_pin(object_id):
        # Evicted (or unpinnable) between put and pin: clean up rather
        # than strand a multi-MB orphan until LRU pressure finds it.
        try:
            store.delete(object_id)
        except KeyError:
            pass
        return None
    return store.descriptor(object_id)


def release_page_blob(store, object_id: ObjectID) -> None:
    """Unpin + delete a consumed handoff blob (idempotent)."""
    store.try_unpin(object_id)
    try:
        store.delete(object_id)
    except KeyError:
        pass


def import_page_blob(desc: tuple):
    """Map a sealed page blob by descriptor: ``("shm", name, nbytes)``
    from the Python per-segment store or ``("shma", segment, off,
    nbytes, key)`` from the native arena.  Returns (value, keepalive) —
    numpy leaves are zero-copy views into the shared mapping for as long
    as the keepalive is held (cross-host consumers instead pull raw
    bytes through the normal transfer path and re-publish locally)."""
    if desc[0] == "shm":
        return RemoteObjectReader.read(desc[1], desc[2])
    if desc[0] == "shma":
        return ArenaReader.read(desc)
    raise ValueError(f"unknown page-blob descriptor kind {desc[0]!r}")


class RemoteObjectReader:
    """Maps sealed objects created by other processes on this host by name."""

    @staticmethod
    def read(shm_name: str, nbytes: int) -> Any:
        shm = _open_untracked(shm_name, create=False)
        try:
            # Deserialized arrays may view the mapping; copy-free read then
            # detach on return: loads with buffers keeps views alive via the
            # returned object, so hold the shm on the object.
            value = serialization.read_payload_from(shm.buf[:nbytes])
            if hasattr(value, "__dict__"):
                try:
                    value.__dict__["_ray_tpu_shm_keepalive"] = shm
                except Exception:
                    pass
            return value, shm
        except Exception:
            shm.close()
            raise

    @staticmethod
    def write(shm_name_unused: str, object_id: ObjectID, value: Any) -> Tuple[str, int]:
        """Create + seal an object segment from a non-owner process."""
        meta, buffers = serialization.serialize_payload(value)
        return RemoteObjectReader.write_payload(object_id, meta, buffers)

    @staticmethod
    def write_payload(object_id: ObjectID, meta: bytes,
                      buffers) -> Tuple[str, int]:
        nbytes = serialization.payload_nbytes(meta, buffers)
        try:
            shm = _open_untracked(_shm_name(object_id), create=True,
                                  size=max(nbytes, 1))
        except FileExistsError:
            # Stale segment from a lost producer (killed node/worker whose
            # cleanup never ran) — lineage re-execution must be able to
            # replace it.
            stale = _open_untracked(_shm_name(object_id), create=False)
            stale.close()
            stale.unlink()
            shm = _open_untracked(_shm_name(object_id), create=True,
                                  size=max(nbytes, 1))
        serialization.write_payload_into(shm.buf[:nbytes], meta, buffers)
        shm.close()
        return _shm_name(object_id), nbytes
