"""Causal (GQA) attention: pallas flash kernel + jnp reference.

The pallas kernel blocks over queries only and keeps each head's full K/V in
VMEM (fine up to ~8k tokens at 128 head_dim; longer sequences use
ring_attention / ulysses which shard the sequence before this kernel runs).
Scores for a [block_q, seq] tile stay in registers/VMEM — the [seq, seq]
matrix is never materialized in HBM, which is the HBM-bandwidth win over
naive attention.  MXU work is two matmuls per tile with fp32 accumulation.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        q_offset: int = 0):
    """Plain-jnp attention. q: [B, H, Sq, D]; k/v: [B, Hkv, Sk, D].

    ``q_offset`` shifts query positions for causal masking (used by
    sequence-sharded callers where the local Q block starts mid-sequence).
    """
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if Hkv != H:
        group = H // Hkv
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = jnp.arange(Sq) + q_offset
        kpos = jnp.arange(Sk)
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q):
    from jax.experimental import pallas as pl
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    k = k_ref[0]                      # [Sk, D]
    v = v_ref[0]
    scores = jax.lax.dot_general(
        q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [block_q, Sk]
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(qpos >= kpos, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / denom
    o_ref[0] = jax.lax.dot(probs.astype(v.dtype), v,
                           preferred_element_type=jnp.float32
                           ).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, interpret):
    """Returns out [B,H,S,D]."""
    from jax.experimental import pallas as pl

    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    if H % Hkv:
        raise ValueError(f"H={H} not divisible by Hkv={Hkv}")
    group = H // Hkv
    block_q = min(block_q, Sq)
    if Sq % block_q:
        raise ValueError(f"seq {Sq} not divisible by block_q {block_q}")

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * Hkv, Sk, D)
    vr = v.reshape(B * Hkv, Sk, D)

    def q_index(bh, qi):
        return (bh, qi, 0)

    def kv_index(bh, qi):
        b = bh // H
        h = bh % H
        return (b * Hkv + h // group, 0, 0)

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=block_q),
        grid=(B * H, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, Sk, D), kv_index),
            pl.BlockSpec((1, Sk, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), q_index),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, Sq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, interpret)
    return out, (q, k, v, out)


def _flash_bwd(causal, scale, block_q, interpret, res, dout):
    """Blocked FA2-style backward in jnp: chunked over q blocks so the
    [Sq, Sk] score matrix only ever exists one block-row at a time; the
    einsums hit the MXU and XLA fuses the elementwise chain.  Softmax is
    recomputed per block (stable, full row available), so the forward saves
    no LSE.  (A dedicated pallas backward kernel is the planned upgrade.)"""
    q, k, v, out = res
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    group = H // Hkv
    kf = jnp.repeat(k, group, axis=1).astype(jnp.float32)
    vf = jnp.repeat(v, group, axis=1).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    do = dout.astype(jnp.float32)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1)  # [B,H,Sq]

    nblk = Sq // min(block_q, Sq)
    bq = Sq // nblk

    def body(carry, i):
        dk, dv = carry
        sl = jax.lax.dynamic_slice_in_dim
        qi = sl(qf, i * bq, bq, axis=2)          # [B,H,bq,D]
        doi = sl(do, i * bq, bq, axis=2)
        deltai = sl(delta, i * bq, bq, axis=2)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qi, kf) * scale
        if causal:
            qpos = i * bq + jnp.arange(bq)
            mask = qpos[:, None] >= jnp.arange(Sk)[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        p = jax.nn.softmax(scores, axis=-1)
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, doi)
        dp = jnp.einsum("bhqd,bhkd->bhqk", doi, vf)
        ds = p * (dp - deltai[..., None]) * scale
        dqi = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, qi)
        return (dk, dv), dqi

    zeros = jnp.zeros((B, H, Sk, D), jnp.float32)
    (dk, dv), dq_blocks = jax.lax.scan(body, (zeros, zeros),
                                       jnp.arange(nblk))
    dq = jnp.moveaxis(dq_blocks, 0, 2).reshape(B, H, Sq, D)
    if group > 1:
        dk = dk.reshape(B, Hkv, group, Sk, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Sk, D).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 256,
                    interpret: bool = False):
    """Pallas flash attention with custom VJP.
    q: [B, H, S, D]; k/v: [B, Hkv, S, D]."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, scale, block_q, interpret)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
              impl: Optional[str] = None):
    """Dispatching entry point: pallas flash on TPU, reference elsewhere."""
    if impl == "reference" or (impl is None and not _on_tpu()):
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=True)
    return flash_attention(q, k, v, causal=causal, scale=scale)
