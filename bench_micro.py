"""Microbenchmark suite mirroring the reference's canonical perf cases
(reference: python/ray/_private/ray_perf.py:95 `main`; recorded baselines in
release/perf_metrics/microbenchmark.json — see BASELINE.md table).

Prints one JSON line per case:
    {"benchmark": "...", "value": N, "unit": "ops/s", "baseline": N}

Run: python bench_micro.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

# Reference numbers from BASELINE.md (release/perf_metrics/microbenchmark.json)
BASELINES = {
    "single_client_tasks_async": 7097.0,
    "single_client_tasks_sync": 813.0,
    "1_1_actor_calls_sync": 1880.0,
    "1_1_actor_calls_async": 8397.0,
    "n_n_actor_calls_async": 23481.0,
    "single_client_put_calls": 4632.0,
    "single_client_get_calls": 10618.0,
    "single_client_put_gigabytes": 12.8,
    "single_client_wait_1k_refs": 4.9,
    "placement_group_create_removal": 657.0,
}


def timeit(name, fn, multiplier=1, *, repeat=3, warmup=1):
    for _ in range(warmup):
        fn()
    best = 0.0
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = max(best, multiplier / dt)
    base = BASELINES.get(name)
    print(json.dumps({
        "benchmark": name, "value": round(best, 2),
        "unit": "GiB/s" if ("gigabytes" in name or "pipeline" in name)
                else "ops/s",
        "baseline": base,
        "vs_baseline": round(best / base, 3) if base else None,
    }), flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    args = p.parse_args()
    scale = 0.2 if args.quick else 1.0

    import ray_tpu

    ray_tpu.init(num_cpus=8, num_tpus=0)

    @ray_tpu.remote
    def small():
        return b"ok"

    # Warm the worker pool so spawn cost isn't measured.
    ray_tpu.get([small.remote() for _ in range(16)])

    n = int(1000 * scale)
    timeit("single_client_tasks_async",
           lambda: ray_tpu.get([small.remote() for _ in range(n)]),
           multiplier=n)

    n_sync = int(200 * scale)

    def sync_tasks():
        for _ in range(n_sync):
            ray_tpu.get(small.remote())
    timeit("single_client_tasks_sync", sync_tasks, multiplier=n_sync)

    @ray_tpu.remote
    class Echo:
        def ping(self):
            return b"ok"

    actor = Echo.remote()
    ray_tpu.get(actor.ping.remote())

    def actor_sync():
        for _ in range(n_sync):
            ray_tpu.get(actor.ping.remote())
    timeit("1_1_actor_calls_sync", actor_sync, multiplier=n_sync)

    n_async = int(1000 * scale)
    timeit("1_1_actor_calls_async",
           lambda: ray_tpu.get([actor.ping.remote() for _ in range(n_async)]),
           multiplier=n_async)

    n_actors = 8
    actors = [Echo.remote() for _ in range(n_actors)]
    ray_tpu.get([a.ping.remote() for a in actors])
    per = int(250 * scale)
    timeit("n_n_actor_calls_async",
           lambda: ray_tpu.get([a.ping.remote() for a in actors
                                for _ in range(per)]),
           multiplier=n_actors * per)

    small_obj = np.zeros(64, np.float64)
    n_put = int(500 * scale)
    timeit("single_client_put_calls",
           lambda: [ray_tpu.put(small_obj) for _ in range(n_put)],
           multiplier=n_put)

    refs = [ray_tpu.put(small_obj) for _ in range(n_put)]

    def gets():
        for r in refs:
            ray_tpu.get(r)
    timeit("single_client_get_calls", gets, multiplier=n_put)

    big = np.zeros(64 * 1024 * 1024 // 8, np.float64)  # 64 MiB
    n_big = max(int(8 * scale), 2)
    gib = n_big * big.nbytes / (1 << 30)
    put_refs = []

    def big_puts():
        put_refs.clear()
        put_refs.extend(ray_tpu.put(big) for _ in range(n_big))

    # A put is ONE memcpy into the arena (serialize_payload is
    # out-of-band: ~0.05ms), so the machine's copy bandwidth INTO shared
    # memory is the physical ceiling.  Mirror the put's memory pattern —
    # n_big distinct shm destinations, not one warm private buffer — and
    # interleave the two measurements best-of, so CPU-steal on a shared
    # box hits both equally and the ratio reads honestly.
    from multiprocessing import shared_memory
    seg = shared_memory.SharedMemory(create=True,
                                     size=n_big * big.nbytes)
    views = [np.frombuffer(seg.buf, np.float64, big.size,
                           offset=i * big.nbytes) for i in range(n_big)]
    best_put, best_ceiling = 0.0, 0.0
    try:
        big_puts()  # warm pool/arena
        for _ in range(4):
            t0 = time.perf_counter()
            big_puts()
            best_put = max(best_put, gib / (time.perf_counter() - t0))
            t0 = time.perf_counter()
            for v in views:
                np.copyto(v, big)
            best_ceiling = max(best_ceiling,
                               gib / (time.perf_counter() - t0))
        del v
    finally:
        del views
        seg.close()
        seg.unlink()
    print(json.dumps({
        "benchmark": "single_client_put_gigabytes",
        "value": round(best_put, 2), "unit": "GiB/s",
        "baseline": BASELINES["single_client_put_gigabytes"],
        "vs_baseline": round(
            best_put / BASELINES["single_client_put_gigabytes"], 3),
    }), flush=True)
    print(json.dumps({
        "benchmark": "hw_memcpy_ceiling", "value": round(best_ceiling, 2),
        "unit": "GiB/s", "baseline": None, "vs_baseline": None,
    }), flush=True)
    print(json.dumps({
        "benchmark": "put_vs_hw_ceiling",
        "value": round(best_put / best_ceiling, 3), "unit": "ratio",
        "baseline": None, "vs_baseline": None,
    }), flush=True)

    @ray_tpu.remote
    def slowish(i):
        return i

    def wait_1k():
        refs = [slowish.remote(i) for i in range(int(1000 * scale))]
        ready, pending = ray_tpu.wait(refs, num_returns=len(refs),
                                      timeout=120)
        assert not pending
    timeit("single_client_wait_1k_refs", wait_1k, multiplier=1)

    n_pg = int(50 * scale)

    def pg_cycle():
        for _ in range(n_pg):
            pg = ray_tpu.placement_group([{"CPU": 1}], strategy="PACK")
            pg.ready(timeout=10)
            ray_tpu.remove_placement_group(pg)
    timeit("placement_group_create_removal", pg_cycle, multiplier=n_pg)

    # -- Data: parquet -> batches pipeline, numpy blocks vs Arrow blocks
    # (zero-copy scan; numpy only at the consumer boundary).
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu import data as rd
    from ray_tpu.data.context import DataContext

    with tempfile.TemporaryDirectory() as td:
        rows = int(2_000_000 * scale)
        t = pa.table({"x": np.arange(rows, dtype=np.int64),
                      "y": np.arange(rows, dtype=np.float64)})
        for i in range(4):
            pq.write_table(t.slice(i * rows // 4, rows // 4),
                           f"{td}/part{i}.parquet")
        gib_data = 2 * rows * 8 / (1 << 30)
        for fmt in ("numpy", "arrow"):
            DataContext.get().block_format = fmt

            def pipeline():
                ds = rd.read_parquet(f"{td}/part*.parquet")
                n = 0
                for b in ds.iter_batches(batch_size=65536):
                    n += len(b["x"])
                assert n == rows
            timeit(f"data_parquet_pipeline_{fmt}", pipeline,
                   multiplier=gib_data)
        DataContext.get().block_format = "numpy"

    ray_tpu.shutdown()

    # -- 2-node cluster variant: the same n:n pattern with the actors on a
    # REMOTE node, driven over the driver's caller->actor direct channels
    # (cluster.py DirectChannel) instead of the in-process fast path.
    # There is no reference baseline for this shape; the single-node
    # n_n baseline is printed for context only.
    from ray_tpu.cluster_utils import Cluster
    with Cluster(head_num_cpus=0) as c:
        c.add_node(num_cpus=4)
        c.add_node(num_cpus=4)
        actors2 = [Echo.remote() for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in actors2])
        per2 = int(125 * scale)
        timeit("n_n_actor_calls_async_2node",
               lambda: ray_tpu.get([a.ping.remote() for a in actors2
                                    for _ in range(per2)]),
               multiplier=n_actors * per2)


if __name__ == "__main__":
    main()
