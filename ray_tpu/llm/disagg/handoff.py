"""KV handoff: the sealed object a prefill worker hands a decode worker.

Reference analog: DistServe/Splitwise-style prefill/decode
disaggregation (and the vLLM KV-connector abstraction the reference's
serving stack reaches through python/ray/llm/_internal/serve/engines/
vllm/) — the prefill tier computes the prompt's KV once, the decode tier
imports it into its own paged cache and joins the request to the
continuous batch.

Transport: the existing host shm object store.  Same-host handoff is
zero-copy — the blob seals into a shared-memory segment and the decode
worker maps it by descriptor (numpy leaves are views; nothing is
re-serialized).  Cross-host consumers ride the normal raw-payload
transfer path and re-publish locally.  With no store at all (one-process
serving, tests) the handoff object passes through directly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import numpy as np

from ...util import telemetry
from ..engine import SamplingParams


@dataclass
class KVHandoff:
    """A prefilled prompt ready to join a decode worker's batch.

    ``ks``/``vs`` are the per-layer K/V for the (bucket-padded) prompt
    in the prefill program's native ``[L, S_pad, Hkv, D]`` layout — the
    exact input of the decode engine's compiled ``write_prefill``
    scatter, so import is ONE device program with no relayouting.
    """

    prompt_tokens: List[int]
    first_token: int
    ks: np.ndarray
    vs: np.ndarray
    params: SamplingParams
    t_submit: float = 0.0     # perf_counter at request submission
    t_first: float = 0.0      # perf_counter when prefill sampled token 0

    @property
    def nbytes(self) -> int:
        return int(self.ks.nbytes) + int(self.vs.nbytes)


def export_handoff(store, object_id, handoff: KVHandoff) -> Optional[tuple]:
    """Seal ``handoff`` into the shm object store; returns the
    descriptor a same-host decode worker imports by (None when the
    store can't hold it — caller hands the object off directly)."""
    from ..._private.object_store import export_page_blob

    t0 = time.perf_counter()
    desc = export_page_blob(store, object_id, handoff)
    if desc is not None:
        telemetry.observe("ray_tpu_llm_kv_transfer_seconds",
                          time.perf_counter() - t0, tags={"op": "export"})
        telemetry.inc("ray_tpu_llm_kv_transfer_bytes_total",
                      handoff.nbytes)
    return desc


def import_handoff(desc: tuple) -> Tuple[KVHandoff, Any]:
    """Map an exported handoff by descriptor (zero-copy on the same
    host).  Returns (handoff, keepalive): the K/V arrays are views into
    the shared mapping for as long as the keepalive is held — the
    decode worker only needs them until its ``write_prefill`` scatter
    lands."""
    from ..._private.object_store import import_page_blob

    t0 = time.perf_counter()
    handoff, keepalive = import_page_blob(desc)
    telemetry.observe("ray_tpu_llm_kv_transfer_seconds",
                      time.perf_counter() - t0, tags={"op": "import"})
    return handoff, keepalive
