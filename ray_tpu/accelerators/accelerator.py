"""Accelerator plugin interface.

The framework is TPU-first, but the node plane's accelerator handling
(detection, chip pinning env, resource naming, slice topology) goes
through this ABC so heterogeneous hosts — CPU-only RL env-runner fleets,
a future GPU ferry tier — plug in without touching the node manager
(reference: python/ray/_private/accelerators/accelerator.py:16
AcceleratorManager ABC + the per-vendor managers registered in
accelerators/__init__.py).

``register_accelerator`` adds a manager; ``all_accelerators`` is what the
node plane iterates to build its resource set and per-worker visibility
env.  TPUAcceleratorManager is the built-in registration.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Type


class AcceleratorManager(ABC):
    """One accelerator family (reference: accelerator.py:16).

    Implementations are stateless namespaces: every method is a class or
    static method so the node plane can use the type object directly.
    """

    # Resource string, e.g. "TPU" — keys the typed ResourceSet.
    resource_name: str = ""

    @staticmethod
    @abstractmethod
    def detect_num_chips() -> int:
        """Accelerators on this host, WITHOUT initializing a runtime
        (device nodes / env probes only — a worker must be able to call
        this before deciding whether to grab the device)."""

    @staticmethod
    @abstractmethod
    def visibility_env(chip_ids: List[int]) -> Dict[str, str]:
        """Env vars that pin a worker process to exactly ``chip_ids``
        (reference: set_current_process_visible_accelerator_ids)."""

    @staticmethod
    def accelerator_type() -> Optional[str]:
        """Family/type string for node labels (e.g. "v5e"), or None."""
        return None

    @staticmethod
    def slice_resources(accelerator_type: str) -> Dict[str, float]:
        """Per-host resource shape for gang-reserving a whole slice/pod
        of ``accelerator_type`` (empty: no multi-host gangs)."""
        return {}


_REGISTRY: Dict[str, Type[AcceleratorManager]] = {}


def register_accelerator(manager: Type[AcceleratorManager]) -> None:
    import inspect
    if not manager.resource_name:
        raise ValueError("accelerator manager needs a resource_name")
    if inspect.isabstract(manager):
        raise TypeError(
            f"{manager.__name__} is missing abstract methods: "
            f"{sorted(getattr(manager, '__abstractmethods__', ()))}")
    _REGISTRY[manager.resource_name] = manager


def all_accelerators() -> List[Type[AcceleratorManager]]:
    return list(_REGISTRY.values())


def get_accelerator(resource_name: str) -> Optional[Type[AcceleratorManager]]:
    return _REGISTRY.get(resource_name)
