"""Model multiplexing: many models per deployment, LRU-cached per replica.

Reference analog: python/ray/serve/multiplex.py (`@serve.multiplexed`
decorating a model-loader method; `serve.get_multiplexed_model_id()` inside
the request path; the router prefers replicas that already hold the model).

Replica side: the decorated loader becomes an LRU cache keyed by model id —
at most ``max_num_models_per_replica`` resident, least-recently-used evicted
(with an optional ``__del__``-style unload hook on the model).  Router side:
the deployment router keeps a model→replica affinity map (it is the sole
entry point, so optimistic tracking stays accurate) and routes a request
for model M to a replica that served M before, falling back to
power-of-two-choices — which is how cache locality survives scaling events.
"""

from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("serve_multiplexed_model_id", default=None)


def get_multiplexed_model_id() -> Optional[str]:
    """The model id of the in-flight request (reference:
    serve.get_multiplexed_model_id) — None outside a multiplexed request."""
    return _current_model_id.get()


def _set_current_model_id(model_id: Optional[str]):
    return _current_model_id.set(model_id)


class _MultiplexWrapper:
    """Bound-method wrapper holding the per-replica LRU of loaded models."""

    def __init__(self, fn: Callable, instance: Any, max_models: int):
        self._fn = fn
        self._instance = instance
        self._max = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        # One lock per model id so concurrent misses for the same model do
        # a single load (loads can take minutes on TPU) instead of racing
        # and leaking the losing duplicate.
        self._load_locks: dict = {}

    @property
    def loaded_model_ids(self):
        with self._lock:
            return list(self._models)

    @staticmethod
    def _unload(model: Any) -> None:
        unload = getattr(model, "unload", None)
        if callable(unload):
            try:
                unload()
            except Exception:  # noqa: BLE001
                pass

    def __call__(self, model_id: Optional[str] = None) -> Any:
        if model_id is None:
            model_id = get_multiplexed_model_id()
        if model_id is None:
            raise ValueError(
                "no model id: pass one explicitly or route the request "
                "with handle.options(multiplexed_model_id=...)")
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            load_lock = self._load_locks.setdefault(model_id,
                                                   threading.Lock())
        with load_lock:
            # A concurrent loader may have finished while we waited.
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
            # Load outside self._lock: cache hits for other models proceed.
            model = self._fn(self._instance, model_id)
            evicted = None
            with self._lock:
                self._models[model_id] = model
                self._models.move_to_end(model_id)
                if len(self._models) > self._max:
                    _, evicted = self._models.popitem(last=False)
                # Load locks are kept (bounded by distinct model ids): a
                # fresh lock per miss would let an evict/reload race load
                # the same model twice and leak the overwritten copy.
        if evicted is not None:
            self._unload(evicted)
        return model


class _MultiplexedDescriptor:
    """Descriptor so `self.get_model` resolves to a per-instance wrapper."""

    def __init__(self, fn: Callable, max_models: int):
        self._fn = fn
        self._max = max_models
        self._attr = f"__multiplex_{fn.__name__}"

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        wrapper = getattr(instance, self._attr, None)
        if wrapper is None:
            wrapper = _MultiplexWrapper(self._fn, instance, self._max)
            setattr(instance, self._attr, wrapper)
        return wrapper


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for a deployment's model-loader method (reference:
    serve/multiplex.py @serve.multiplexed).

        @serve.deployment
        class Model:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                return load_model(model_id)

            def __call__(self, x):
                model = self.get_model()   # current request's model
                return model(x)
    """
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def deco(fn: Callable) -> _MultiplexedDescriptor:
        return _MultiplexedDescriptor(fn, max_num_models_per_replica)
    return deco


class RouterAffinity:
    """Router-side model→replica affinity with per-replica LRU mirroring
    the replica cache size (reference: the controller's model-id long-poll
    feed into the router; here the router is the single entry point so it
    tracks assignments directly)."""

    def __init__(self, max_models_per_replica: int = 8):
        self._max = max_models_per_replica
        # replica key -> LRU of model ids
        self._by_replica: "OrderedDict[int, OrderedDict[str, None]]" = \
            OrderedDict()
        self._lock = threading.Lock()

    def replicas_for(self, model_id: str):
        with self._lock:
            return [rk for rk, models in self._by_replica.items()
                    if model_id in models]

    def note(self, replica_key: int, model_id: str) -> None:
        with self._lock:
            models = self._by_replica.setdefault(replica_key, OrderedDict())
            if model_id in models:
                models.move_to_end(model_id)
            else:
                models[model_id] = None
                if len(models) > self._max:
                    models.popitem(last=False)

    def drop_replica(self, replica_key: int) -> None:
        with self._lock:
            self._by_replica.pop(replica_key, None)
