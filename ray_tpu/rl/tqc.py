"""TQC: truncated quantile critics for continuous control.

Reference: rllib/algorithms/tqc/ (SAC with an ensemble of distributional
critics; overestimation is controlled by dropping the top quantiles of
the pooled target distribution instead of clipped double-Q).  Built on
the SAC scaffolding: the whole update — quantile critics, actor,
temperature, polyak — is one jitted function of (state, batch, key).

The critic ensemble is a single vmapped MLP (leading axis = critic):
one XLA program evaluates all N critics as a batched matmul stack —
the TPU-friendly layout (no Python loop over ensemble members).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import numpy as np

from .algorithm import Algorithm
from .env import make_env
from .replay_buffer import ReplayBuffer
from .rl_module import (ContinuousModuleSpec, GaussianPolicyModule,
                        _init_mlp, _mlp)
from .sac import SAC, SACConfig


class TQCState(NamedTuple):
    pi_params: Any
    z_params: Any     # quantile critic ensemble
    z_target: Any
    log_alpha: Any
    pi_opt: Any
    z_opt: Any
    alpha_opt: Any


class QuantileCriticEnsemble:
    """N critics x M quantiles of Z(s, a), vmapped over the ensemble."""

    def __init__(self, spec: ContinuousModuleSpec, num_critics: int,
                 num_quantiles: int):
        self.spec = spec
        self.n = num_critics
        self.m = num_quantiles

    def init(self, key):
        import jax
        dims = (self.spec.observation_dim + self.spec.action_dim,
                *self.spec.hidden, self.m)
        keys = jax.random.split(key, self.n)
        per = [_init_mlp(k, dims) for k in keys]
        return jax.tree.map(lambda *xs: jax.numpy.stack(xs), *per)

    def quantiles(self, params, obs, actions):
        """-> [N, B, M]."""
        import jax
        import jax.numpy as jnp
        x = jnp.concatenate([obs, actions], axis=-1)
        return jax.vmap(_mlp, in_axes=(0, None))(params, x)


def _quantile_huber(pred, target, taus, kappa: float = 1.0):
    """pred [B, M]; target [B, K] (stop-gradded); taus [M] -> scalar."""
    import jax.numpy as jnp
    delta = target[:, None, :] - pred[:, :, None]          # [B, M, K]
    abs_d = jnp.abs(delta)
    huber = jnp.where(abs_d <= kappa, 0.5 * delta ** 2,
                      kappa * (abs_d - 0.5 * kappa))
    weight = jnp.abs(taus[None, :, None]
                     - (delta < 0).astype(jnp.float32))
    return jnp.mean(jnp.sum(weight * huber, axis=1) / kappa)


class TQCConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = TQC
        self.num_critics = 3
        self.num_quantiles = 13
        self.top_quantiles_to_drop = 2  # per critic

    def training(self, *, num_critics=None, num_quantiles=None,
                 top_quantiles_to_drop=None, **kw) -> "TQCConfig":
        super().training(**kw)
        if num_critics is not None:
            self.num_critics = num_critics
        if num_quantiles is not None:
            self.num_quantiles = num_quantiles
        if top_quantiles_to_drop is not None:
            self.top_quantiles_to_drop = top_quantiles_to_drop
        return self


class TQC(Algorithm):
    """Off-policy, drives its own env loop (SAC scaffolding)."""

    _use_env_runner_group = False

    def setup(self, config: TQCConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        env = make_env(config.env_spec)
        if not env.is_continuous:
            raise ValueError("TQC requires a continuous-action env")
        self.env = env
        spec = ContinuousModuleSpec(env.observation_dim, env.action_dim,
                                    env.action_low, env.action_high,
                                    tuple(config.module_hidden))
        self.pi = GaussianPolicyModule(spec)
        self.z = QuantileCriticEnsemble(spec, config.num_critics,
                                        config.num_quantiles)
        n, m = config.num_critics, config.num_quantiles
        kept = n * (m - config.top_quantiles_to_drop)
        if kept <= 0:
            raise ValueError("top_quantiles_to_drop leaves no target atoms")
        taus = (2 * jnp.arange(m, dtype=jnp.float32) + 1) / (2 * m)
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(env.action_dim))
        pi_optim = optax.adam(config.actor_lr or config.lr)
        z_optim = optax.adam(config.critic_lr or config.lr)
        alpha_optim = optax.adam(config.alpha_lr)
        gamma, tau_polyak = config.gamma, config.tau

        key = jax.random.key(config.seed)
        kp, kz = jax.random.split(key)
        pi_params = self.pi.init(kp)
        z_params = self.z.init(kz)
        log_alpha = jnp.log(jnp.asarray(config.initial_alpha, jnp.float32))
        self.state = TQCState(
            pi_params, z_params, z_params, log_alpha,
            pi_optim.init(pi_params), z_optim.init(z_params),
            alpha_optim.init(log_alpha))

        pi, z = self.pi, self.z

        def update(state: TQCState, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(state.log_alpha)

            # -- critics: truncated pooled target distribution ------------
            next_a, next_logp = pi.sample(state.pi_params,
                                          batch["next_obs"], k1)
            tz = z.quantiles(state.z_target, batch["next_obs"], next_a)
            B = tz.shape[1]
            pooled = jnp.sort(jnp.transpose(tz, (1, 0, 2)).reshape(B, -1),
                              axis=-1)[:, :kept]          # drop top atoms
            target = batch["rewards"][:, None] + gamma * \
                (1.0 - batch["terminateds"])[:, None] * \
                (pooled - alpha * next_logp[:, None])
            target = jax.lax.stop_gradient(target)

            def critic_loss(zp):
                qs = z.quantiles(zp, batch["obs"], batch["actions"])
                loss = sum(_quantile_huber(qs[i], target, taus)
                           for i in range(n)) / n
                return loss, jnp.mean(qs)

            (closs, z_mean), z_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state.z_params)
            z_updates, z_opt = z_optim.update(z_grads, state.z_opt,
                                              state.z_params)
            z_params = optax.apply_updates(state.z_params, z_updates)

            # -- actor: maximize mean of ALL quantiles - alpha log pi -----
            def actor_loss(pp):
                a, logp = pi.sample(pp, batch["obs"], k2)
                qs = z.quantiles(z_params, batch["obs"], a)
                return jnp.mean(alpha * logp - jnp.mean(qs, axis=(0, 2))), \
                    jnp.mean(logp)

            (aloss, logp_mean), pi_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state.pi_params)
            pi_updates, pi_opt = pi_optim.update(pi_grads, state.pi_opt,
                                                 state.pi_params)
            pi_params = optax.apply_updates(state.pi_params, pi_updates)

            # -- temperature ----------------------------------------------
            def alpha_loss(la):
                return -jnp.exp(la) * jax.lax.stop_gradient(
                    logp_mean + target_entropy)

            _, a_grads = jax.value_and_grad(alpha_loss)(state.log_alpha)
            a_updates, alpha_opt = alpha_optim.update(a_grads,
                                                      state.alpha_opt)
            log_alpha = optax.apply_updates(state.log_alpha, a_updates)

            z_target = jax.tree.map(
                lambda t, o: (1 - tau_polyak) * t + tau_polyak * o,
                state.z_target, z_params)
            metrics = {"critic_loss": closs, "actor_loss": aloss,
                       "alpha": alpha, "z_mean": z_mean,
                       "logp_mean": logp_mean}
            return TQCState(pi_params, z_params, z_target, log_alpha,
                            pi_opt, z_opt, alpha_opt), metrics

        self._update = jax.jit(update)
        self._sample_act = jax.jit(pi.sample)
        self._infer_act = jax.jit(pi.forward_inference)

        self.buffer = ReplayBuffer(config.buffer_size, seed=config.seed)
        self._key = jax.random.key(config.seed + 1)
        self._obs, _ = self.env.reset(seed=config.seed)
        self._steps = 0
        self._rng = np.random.default_rng(config.seed)
        self._ep_return = 0.0
        self._returns: list = []

    # Env loop identical to SAC's (same state/act/update contract).
    _act = SAC._act
    training_step = SAC.training_step

    def get_weights(self):
        return {"pi": self.state.pi_params, "z": self.state.z_params,
                "z_target": self.state.z_target,
                "log_alpha": self.state.log_alpha}

    def set_weights(self, params) -> None:
        self.state = self.state._replace(
            pi_params=params["pi"], z_params=params["z"],
            z_target=params["z_target"], log_alpha=params["log_alpha"])

    def compute_single_action(self, obs: np.ndarray,
                              explore: bool = False) -> np.ndarray:
        import jax
        if explore:
            self._key, sub = jax.random.split(self._key)
            a, _ = self._sample_act(self.state.pi_params, obs[None], sub)
            return np.asarray(a)[0]
        return np.asarray(self._infer_act(self.state.pi_params,
                                          obs[None]))[0]
