"""Node memory monitor + OOM worker-killing policy.

Reference analog: src/ray/common/memory_monitor (MemoryMonitorInterface
memory_monitor_interface.h:86, threshold/pressure monitors) feeding the
raylet's worker-killing policies (src/ray/raylet/worker_killing_policy*.h).

The monitor samples node memory (cgroup-v2 limits when the process is
inside a bounded cgroup, /proc/meminfo otherwise), and when usage crosses
the configured threshold it asks the kill policy for a victim worker and
SIGKILLs it.  The runtime's existing worker-death path then retries the
killed task (if retriable) or fails it with an OOM-flavored error.

Victim selection mirrors the reference's retriable-LIFO policy
(worker_killing_policy_retriable_fifo.h): prefer workers whose running
tasks can be retried, and among those kill the most recently started —
protecting long-running work and never starving the node of progress
(the earliest-started non-retriable worker is killed only as last resort).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from .config import Config

_CGROUP_MAX = "/sys/fs/cgroup/memory.max"
_CGROUP_CUR = "/sys/fs/cgroup/memory.current"


@dataclass
class MemorySnapshot:
    used_bytes: int
    total_bytes: int

    @property
    def fraction(self) -> float:
        return self.used_bytes / self.total_bytes if self.total_bytes else 0.0


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
        return None if raw == "max" else int(raw)
    except (OSError, ValueError):
        return None


def system_memory() -> MemorySnapshot:
    """Node memory usage: bounded cgroup v2 if present, else /proc/meminfo."""
    limit = _read_int(_CGROUP_MAX)
    current = _read_int(_CGROUP_CUR)
    if limit is not None and current is not None:
        return MemorySnapshot(current, limit)
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        pass
    return MemorySnapshot(max(total - avail, 0), total)


def process_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def select_victim(candidates: List[Tuple[object, bool, float]]) -> Optional[object]:
    """Pick the worker to kill from (handle, retriable, earliest_start) rows.

    Retriable-last-started first; non-retriable workers only when no
    retriable candidate exists, and then also last-started (the reference
    kills LIFO within each group so the oldest work survives).
    """
    if not candidates:
        return None
    retriable = [c for c in candidates if c[1]]
    group = retriable if retriable else candidates
    return max(group, key=lambda c: c[2])[0]


class MemoryMonitor:
    """Polls memory usage and OOM-kills workers above the threshold.

    ``usage_fn`` is injectable for tests; the ``memory_monitor_test_fraction``
    config flag overrides the observed usage fraction so integration tests can
    trip the killer deterministically from another process.
    """

    def __init__(self, node_manager,
                 usage_fn: Callable[[], MemorySnapshot] = system_memory):
        self._node = node_manager
        self._usage_fn = usage_fn
        self._threshold = Config.get("memory_usage_threshold")
        self._period_s = Config.get("memory_monitor_refresh_ms") / 1000.0
        self._min_interval_s = Config.get("memory_monitor_kill_interval_s")
        self._last_kill = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._period_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="memory-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._period_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — monitor must never die
                pass

    def snapshot(self) -> MemorySnapshot:
        fake = Config.get("memory_monitor_test_fraction")
        if fake > 0:
            return MemorySnapshot(int(fake * 1e9), int(1e9))
        return self._usage_fn()

    def check_once(self) -> Optional[object]:
        """One poll; returns the killed worker handle (or None)."""
        snap = self.snapshot()
        if snap.fraction < self._threshold:
            return None
        now = time.monotonic()
        if now - self._last_kill < self._min_interval_s:
            return None
        victim = self._node.select_oom_victim()
        if victim is None:
            return None
        self._last_kill = now
        self._node.oom_kill_worker(
            victim,
            f"node memory usage {snap.fraction:.0%} "
            f"({snap.used_bytes >> 20} MiB / {snap.total_bytes >> 20} MiB) "
            f"over threshold {self._threshold:.0%}")
        return victim
