"""Collective backends: XLA (jax.distributed) and KV (control-plane).

Rendezvous protocol (both backends): rank 0 publishes group metadata at
``collective/<group>/meta`` in the runtime KV store; every member then
checks in at ``collective/<group>/join/<rank>``.  This replaces the
reference's named-store-actor NCCL-unique-id exchange (reference:
python/ray/util/collective/collective_group/nccl_collective_group.py:36).
"""

from __future__ import annotations

import pickle
import socket
import time
from typing import Any

_RENDEZVOUS_TIMEOUT_S = 120.0
_POLL_S = 0.02


def _kv_put(key: str, value: bytes) -> None:
    from .._private.api import _control
    _control("kv_put", key, value)


def _kv_get(key: str):
    from .._private.api import _control
    return _control("kv_get", key)


def _kv_del(key: str) -> None:
    from .._private.api import _control
    _control("kv_del", key)


def _wait_for(key: str, timeout: float = _RENDEZVOUS_TIMEOUT_S) -> bytes:
    """Blocking server-side wait (controller condvar, ctl_kv_wait) — the
    writer's kv_put wakes us; no client poll loop.  Chunked so a lost
    reply can't strand the caller past the deadline."""
    deadline = time.monotonic() + timeout
    from .._private.api import _control
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"rendezvous timed out waiting for {key}")
        v = _control("kv_wait", key, timeout=min(remaining, 10.0))
        if v is not None:
            return v


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _SocketP2P:
    """Direct rank-to-rank transport for send/recv.

    Replaces the round-1 pickle-over-KV polling path: each rank lazily
    opens a TCP listener (address published once through the KV
    rendezvous), peers keep persistent connections, and frames are
    (src_rank, payload) messages demultiplexed into per-source queues.
    The reference's analog is NCCL p2p inside a group
    (nccl_collective_group.py send/recv); on TPU, device tensors should
    ride ppermute inside jit — this path carries host-side numpy.
    """

    def __init__(self, group_name: str, rank: int):
        self.group = group_name
        self.rank = rank
        self.token: bytes = b""
        self._listener = None
        self._out: dict = {}          # dst rank -> Connection
        self._in_queues: dict = {}    # src rank -> queue.Queue
        self._qlock = None
        self._closed = False

    # -- wiring -------------------------------------------------------------

    def _addr_key(self, rank: int) -> str:
        return f"collective/{self.group}/p2p_addr/{rank}"

    def _ensure_token(self) -> None:
        """Group transport secret, minted by rank 0 and distributed over
        the cluster's authenticated control channel (the KV store) — the
        listener unpickles peer frames, so a guessable key would be remote
        code execution for anyone who can reach the port."""
        if self.token:
            return
        key = f"collective/{self.group}/p2p_token"
        if self.rank == 0:
            import os as _os
            self.token = _os.urandom(16)
            _kv_put(key, self.token)
        else:
            self.token = bytes(_wait_for(key))

    def ensure_listener(self) -> None:
        if self._listener is not None:
            return
        import os
        import threading
        from multiprocessing.connection import Listener
        self._ensure_token()
        self._qlock = threading.Lock()
        # Bind the wildcard but advertise a peer-reachable host so ranks
        # on different nodes can connect (same convention as the cluster
        # data plane, cluster.py DataServer).
        self._listener = Listener(("0.0.0.0", 0), authkey=self.token)
        advertise = os.environ.get("RAY_TPU_ADVERTISE_HOST", "127.0.0.1")
        _kv_put(self._addr_key(self.rank),
                pickle.dumps((advertise, self._listener.address[1])))
        self._acceptor = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"p2p-accept-{self.group}-{self.rank}")
        self._acceptor.start()

    def _accept_loop(self) -> None:
        import threading
        while not self._closed:
            try:
                conn = self._listener.accept()
            except Exception:
                # A peer dying mid-handshake must not kill the accept
                # loop; only exit when this endpoint is closing.
                if self._closed:
                    return
                continue
            if self._closed:
                try:
                    conn.close()
                except Exception:
                    pass
                return
            from .._private import sanitizer
            sanitizer.spawn(self._reader, args=(conn,),
                            name="collective-reader")

    def _reader(self, conn) -> None:
        import queue as _q
        while not self._closed:
            try:
                src, payload = conn.recv()
            except (EOFError, OSError):
                return
            with self._qlock:
                q = self._in_queues.setdefault(src, _q.Queue())
            q.put(payload)

    def send(self, dst_rank: int, payload: bytes) -> None:
        from multiprocessing.connection import Client
        conn = self._out.get(dst_rank)
        if conn is None:
            self._ensure_token()
            addr = pickle.loads(_wait_for(self._addr_key(dst_rank)))
            conn = Client(tuple(addr), authkey=self.token)
            self._out[dst_rank] = conn
        conn.send((self.rank, payload))

    def recv(self, src_rank: int,
             timeout: float = _RENDEZVOUS_TIMEOUT_S) -> bytes:
        import queue as _q
        self.ensure_listener()
        with self._qlock:
            q = self._in_queues.setdefault(src_rank, _q.Queue())
        try:
            return q.get(timeout=timeout)
        except _q.Empty:
            raise TimeoutError(
                f"p2p recv from rank {src_rank} timed out") from None

    def close(self) -> None:
        self._closed = True
        for conn in self._out.values():
            try:
                conn.close()
            except Exception:
                pass
        if self._listener is not None:
            # Unblock + join the acceptor before closing the fd (see
            # cluster._drain_acceptor: a blocked accept on a closed fd can
            # adopt a reused fd and steal a newer listener's handshakes).
            from .._private.cluster import _drain_acceptor
            _drain_acceptor(self._listener, self._acceptor)
            try:
                self._listener.close()
            except Exception:
                pass
            _kv_del(self._addr_key(self.rank))
        if self.rank == 0 and self.token:
            _kv_del(f"collective/{self.group}/p2p_token")


class XlaBackend:
    """Group ops lower to XLA collectives over a jax.distributed world.

    On CPU the world uses gloo; on TPU the mesh forms over ICI/DCN via
    libtpu (the JaxTrainer seam, reference: train/v2/jax/config.py:115-133).
    jax.distributed supports one world per process: one XlaBackend group
    may be active at a time in a given worker.
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._mesh = None
        self._np = None
        # (kind, op, shape, dtype) -> compiled fn.  jit caches by callable
        # identity, so fresh lambdas per call would re-trace every op.
        self._jit_cache: dict = {}
        self._p2p = _SocketP2P(group_name, rank)

    def setup(self) -> None:
        # Open the p2p listener up-front so a peer's first send never has
        # to wait for this rank's first recv to publish the address.
        self._p2p.ensure_listener()
        key = f"collective/{self.group_name}/addr"
        if self.rank == 0:
            addr = f"127.0.0.1:{_free_port()}"
            _kv_put(key, addr.encode())
        else:
            addr = _wait_for(key).decode()

        import os

        import jax
        # Must not touch the backend (jax.devices/default_backend) before
        # distributed.initialize.  Platform comes from env only.
        if "tpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass
        jax.distributed.initialize(addr, num_processes=self.world_size,
                                   process_id=self.rank)
        import numpy as np
        from jax.sharding import Mesh
        self._np = np
        devs = jax.devices()
        self._mesh = Mesh(np.array(devs), ("world",))
        self._devices_per_proc = len(jax.local_devices())

    def teardown(self) -> None:
        self._p2p.close()
        try:
            import jax
            jax.distributed.shutdown()
        except Exception:
            pass
        if self.rank == 0:
            _kv_del(f"collective/{self.group_name}/addr")

    # -- helpers ------------------------------------------------------------

    def _global(self, local):
        """Local [*, ...] -> global [n_devices, ...] sharded on axis 0.

        With d devices per process the local row appears d times — as a
        zero-copy broadcast view, not a materialized repeat; reductions
        de-duplicate with a stride-d slice so multi-device processes
        contribute once.
        """
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        local = np.ascontiguousarray(local)
        sharding = NamedSharding(self._mesh, P("world"))
        view = np.broadcast_to(local[None],
                               (self._devices_per_proc, *local.shape))
        return jax.make_array_from_process_local_data(sharding, view)

    def _replicated_result(self, kind: str, computation, arr, op: str = ""):
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        cache_key = (kind, op, arr.shape, str(arr.dtype))
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            fn = jax.jit(computation,
                         out_shardings=NamedSharding(self._mesh, P()))
            self._jit_cache[cache_key] = fn
        out = fn(arr)
        return np.asarray(out.addressable_shards[0].data)

    @staticmethod
    def _op_fn(op: str):
        import jax.numpy as jnp
        return {"sum": jnp.sum, "prod": jnp.prod, "min": jnp.min,
                "max": jnp.max}[op]

    # -- ops ----------------------------------------------------------------

    def allreduce(self, tensor, op: str = "sum"):
        fn = self._op_fn(op)
        arr = self._global(tensor)
        k = self._devices_per_proc
        return self._replicated_result(
            "allreduce", lambda a: fn(a[::k], axis=0), arr, op)

    def allgather(self, tensor):
        arr = self._global(tensor)
        k = self._devices_per_proc
        return self._replicated_result("allgather", lambda a: a[::k], arr)

    def reducescatter(self, tensor, op: str = "sum"):
        """Input per rank: [world * chunk, ...]; returns this rank's chunk."""
        full = self.allreduce(tensor, op)
        n = full.shape[0]
        if n % self.world_size:
            raise ValueError(
                f"reducescatter dim {n} not divisible by {self.world_size}")
        chunk = n // self.world_size
        return full[self.rank * chunk:(self.rank + 1) * chunk]

    def broadcast(self, tensor, src_rank: int = 0):
        import numpy as np
        local = np.asarray(tensor)
        masked = local if self.rank == src_rank else np.zeros_like(local)
        return self.allreduce(masked, "sum")

    def reduce(self, tensor, dst_rank: int = 0, op: str = "sum"):
        out = self.allreduce(tensor, op)
        import numpy as np
        return out if self.rank == dst_rank else np.asarray(tensor)

    def barrier(self) -> None:
        import numpy as np
        self.allreduce(np.zeros(1, np.float32), "sum")

    def send(self, tensor, dst_rank: int) -> None:
        import numpy as np
        self._p2p.send(dst_rank, pickle.dumps(np.asarray(tensor)))

    def recv(self, shape, dtype, src_rank: int):
        return pickle.loads(self._p2p.recv(src_rank))


class KVBackend:
    """Pure-Python collective over the runtime KV store.

    The gloo-equivalent control-plane fallback (SURVEY §2.4 collectives
    row): correct for any picklable numpy payload, no jax required.  Each
    op round gets a sequence number so groups can run many ops.
    """

    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0
        self._nonce = ""
        self._p2p = _SocketP2P(group_name, rank)

    def setup(self) -> None:
        self._p2p.ensure_listener()
        # Rank 0 publishes a fresh incarnation nonce so a recreated group
        # with the same name can never read a previous incarnation's rounds.
        meta_key = f"collective/{self.group_name}/meta"
        if self.rank == 0:
            import uuid
            self._nonce = uuid.uuid4().hex[:8]
            _kv_put(meta_key, self._nonce.encode())
        else:
            self._nonce = _wait_for(meta_key).decode()
        base = f"collective/{self.group_name}/{self._nonce}"
        _kv_put(f"{base}/join/{self.rank}", b"1")
        deadline = time.monotonic() + _RENDEZVOUS_TIMEOUT_S
        for r in range(self.world_size):
            _wait_for(f"{base}/join/{r}", deadline - time.monotonic())

    def teardown(self) -> None:
        self._p2p.close()
        base = f"collective/{self.group_name}/{self._nonce}"
        _kv_del(f"{base}/join/{self.rank}")
        for s in (self._seq, self._seq - 1):
            if s > 0:
                _kv_del(f"{base}/r{s}/{self.rank}")
        if self.rank == 0:
            _kv_del(f"collective/{self.group_name}/meta")

    def _round(self, tensor) -> list:
        """Exchange: everyone publishes, everyone reads all.

        Garbage collection: entering round n proves every rank finished
        round n-1 (we read all its keys), which proves every rank had
        finished reading round n-2 — so each rank deletes its own n-2 key
        here, bounding KV growth to two rounds.
        """
        import numpy as np
        self._seq += 1
        base = f"collective/{self.group_name}/{self._nonce}"
        if self._seq >= 3:
            _kv_del(f"{base}/r{self._seq - 2}/{self.rank}")
        _kv_put(f"{base}/r{self._seq}/{self.rank}",
                pickle.dumps(np.asarray(tensor)))
        parts = []
        for r in range(self.world_size):
            parts.append(pickle.loads(
                _wait_for(f"{base}/r{self._seq}/{r}")))
        return parts

    @staticmethod
    def _reduce(parts: list, op: str):
        import numpy as np
        fns = {"sum": np.add, "prod": np.multiply, "min": np.minimum,
               "max": np.maximum}
        out = parts[0].copy()
        for p in parts[1:]:
            out = fns[op](out, p)
        return out

    def allreduce(self, tensor, op: str = "sum"):
        return self._reduce(self._round(tensor), op)

    def allgather(self, tensor):
        import numpy as np
        return np.stack(self._round(tensor))

    def reducescatter(self, tensor, op: str = "sum"):
        full = self.allreduce(tensor, op)
        if full.shape[0] % self.world_size:
            raise ValueError(
                f"reducescatter dim {full.shape[0]} not divisible by "
                f"{self.world_size}")
        chunk = full.shape[0] // self.world_size
        return full[self.rank * chunk:(self.rank + 1) * chunk]

    def broadcast(self, tensor, src_rank: int = 0):
        parts = self._round(tensor)
        return parts[src_rank]

    def reduce(self, tensor, dst_rank: int = 0, op: str = "sum"):
        out = self.allreduce(tensor, op)
        import numpy as np
        return out if self.rank == dst_rank else np.asarray(tensor)

    def barrier(self) -> None:
        import numpy as np
        self._round(np.zeros(1))

    def send(self, tensor, dst_rank: int) -> None:
        import numpy as np
        self._p2p.send(dst_rank, pickle.dumps(np.asarray(tensor)))

    def recv(self, shape, dtype, src_rank: int):
        return pickle.loads(self._p2p.recv(src_rank))
