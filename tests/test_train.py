"""JaxTrainer e2e tests: MLP SFT on 1- and 2-worker CPU worlds, with
checkpoint/restore and failure recovery (reference test pattern:
python/ray/train/v2/tests/test_controller.py + test_jax_elastic_e2e.py)."""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer, RunConfig,
                           ScalingConfig)


def _mlp_train_fn(config):
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu.train as train
    from ray_tpu.models import MLPConfig, init_mlp, mlp_loss

    ctx = train.get_context()
    cfg = MLPConfig(in_dim=8, hidden=16, out_dim=4)
    start_step = 0
    ckpt = ctx.get_checkpoint()
    if ckpt is not None:
        state = ckpt.load_pytree()
        params = state["params"]
        start_step = int(state["step"])
    else:
        params = init_mlp(cfg, jax.random.key(0))

    rng = np.random.default_rng(ctx.get_world_rank())
    grad_fn = jax.jit(jax.value_and_grad(mlp_loss))
    for step in range(start_step, config["steps"]):
        x = rng.normal(size=(16, 8)).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.int32) % 4
        loss, grads = grad_fn(params, {"x": jnp.asarray(x),
                                       "y": jnp.asarray(y)})
        params = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        if ctx.get_world_rank() == 0:
            ckpt_dir = os.path.join(ctx.storage_path,
                                    ctx.get_experiment_name(),
                                    f"step_{step:04d}")
            cp = Checkpoint.from_pytree(
                {"params": params, "step": step + 1}, ckpt_dir)
            train.report({"loss": float(loss), "step": step}, checkpoint=cp)
        else:
            train.report({"loss": float(loss), "step": step})
        if config.get("die_at_step") is not None and \
                step == config["die_at_step"] and \
                not os.path.exists(config["die_marker"]):
            open(config["die_marker"], "w").close()
            os._exit(1)


class TestJaxTrainerSingle:
    def test_single_worker_e2e(self, ray_start):
        with tempfile.TemporaryDirectory() as tmp:
            trainer = JaxTrainer(
                _mlp_train_fn,
                train_loop_config={"steps": 5},
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(name="single", storage_path=tmp))
            result = trainer.fit()
            assert result.error is None
            assert result.metrics["step"] == 4
            assert result.checkpoint is not None
            state = result.checkpoint.load_pytree()
            assert state["step"] == 5

    def test_failure_recovery_resumes_from_checkpoint(self, ray_start):
        with tempfile.TemporaryDirectory() as tmp:
            marker = os.path.join(tmp, "died_once")
            trainer = JaxTrainer(
                _mlp_train_fn,
                train_loop_config={"steps": 6, "die_at_step": 3,
                                   "die_marker": marker},
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(
                    name="recovery", storage_path=tmp,
                    failure_config=FailureConfig(max_failures=1)))
            result = trainer.fit()
            assert result.error is None
            assert result.num_failures == 1
            # Steps 0..3 ran in attempt 1 (checkpointed through step 3),
            # attempt 2 resumed from step 4, not from scratch.
            steps = sorted(r["metrics"]["step"]
                           for r in result.all_reports)
            assert steps.count(0) == 1, "did not resume from checkpoint"
            assert result.metrics["step"] == 5

    def test_failure_budget_exhausted(self, ray_start):
        def always_dies(config):
            import os
            os._exit(1)
        with tempfile.TemporaryDirectory() as tmp:
            trainer = JaxTrainer(
                always_dies,
                train_loop_config={},
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(
                    name="dead", storage_path=tmp,
                    failure_config=FailureConfig(max_failures=1)))
            result = trainer.fit()
            assert result.error is not None
            assert result.num_failures == 2


def _ddp_train_fn(config):
    """2-process DDP: global mesh over both workers' CPU devices, psum'd
    gradients via GSPMD batch sharding."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import ray_tpu.train as train
    from ray_tpu.models import MLPConfig, init_mlp, mlp_loss

    ctx = train.get_context()
    assert jax.process_count() == 2
    cfg = MLPConfig(in_dim=8, hidden=16, out_dim=4)
    params = init_mlp(cfg, jax.random.key(0))
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rep = NamedSharding(mesh, P())
    params = jax.device_put(params, rep)

    @jax.jit
    def step(params, batch):
        loss, grads = jax.value_and_grad(mlp_loss)(params, batch)
        new = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        return loss, new

    rng = np.random.default_rng(ctx.get_world_rank())
    bsharding = NamedSharding(mesh, P("dp"))
    for i in range(config["steps"]):
        x_local = rng.normal(size=(8, 8)).astype(np.float32)
        y_local = (x_local.sum(axis=1) > 0).astype(np.int32) % 4
        batch = {
            "x": jax.make_array_from_process_local_data(bsharding, x_local),
            "y": jax.make_array_from_process_local_data(bsharding, y_local),
        }
        loss, params = step(params, batch)
        train.report({"loss": float(loss), "step": i})


class TestJaxTrainerDDP:
    def test_two_worker_ddp(self, ray_start):
        with tempfile.TemporaryDirectory() as tmp:
            trainer = JaxTrainer(
                _ddp_train_fn,
                train_loop_config={"steps": 3},
                scaling_config=ScalingConfig(num_workers=2),
                run_config=RunConfig(name="ddp", storage_path=tmp))
            result = trainer.fit()
            assert result.error is None
            assert result.metrics["step"] == 2
            # Both ranks saw identical (replicated) loss each step.
            by_step = {}
            for r in result.all_reports:
                by_step.setdefault(r["metrics"]["step"], []).append(
                    r["metrics"]["loss"])
            for step, losses in by_step.items():
                assert len(losses) == 2
                assert abs(losses[0] - losses[1]) < 1e-6
