"""RL breadth tests: SAC, offline (BC/MARWIL/CQL), multi-agent, connectors,
IMPALA/V-trace.

Reference test analogs: rllib/algorithms/{sac,bc,marwil,cql,impala}/tests,
rllib/env/tests/test_multi_agent_env.py, rllib/connectors tests.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (BCConfig, CQLConfig, ConnectorPipeline, FrameStack,
                        IMPALAConfig, MARWILConfig, MeanStdFilter,
                        MultiAgentPPOConfig, MultiGuess, OfflineData,
                        PPOConfig, SACConfig, collect_from_env, make_env,
                        vtrace)


@pytest.fixture(scope="module")
def offline_dataset(tmp_path_factory):
    """Mixed expert/random behavior data on StatelessGuess."""
    d = tmp_path_factory.mktemp("offline")

    def behavior(obs, rng):
        if rng.random() < 0.3:
            return int(rng.integers(4))
        return int(np.argmax(obs))

    path = collect_from_env("StatelessGuess", behavior, 4000,
                            os.path.join(str(d), "shard-0.npz"), seed=0)
    return path


def _greedy_accuracy(algo, n: int = 100) -> int:
    env = make_env("StatelessGuess")
    acc = 0
    for i in range(n):
        obs, _ = env.reset(seed=i)
        acc += int(algo.compute_single_action(obs) == int(np.argmax(obs)))
    return acc


class TestSAC:
    def test_learns_target_reach(self):
        cfg = (SACConfig().environment("TargetReach")
               .training(lr=3e-3, learning_starts=200, train_batch_size=64)
               .env_runners(rollout_fragment_length=200)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        # Random play scores ~-0.5; learned policy approaches 0.
        assert r["env_runners"]["episode_return_mean"] > -0.15
        # Deterministic policy tracks the target.
        errs = [abs(float(algo.compute_single_action(
            np.array([t], np.float32))[0]) - t)
            for t in np.linspace(-0.8, 0.8, 9)]
        assert max(errs) < 0.25
        # Auto-tuned temperature moved off its initial value.
        assert r["learner"]["alpha"] != pytest.approx(0.2, abs=1e-4)

    def test_rejects_discrete_env(self):
        with pytest.raises(ValueError, match="continuous"):
            (SACConfig().environment("CartPole-v1")).build_algo()

    def test_checkpoint_roundtrip(self, tmp_path):
        cfg = (SACConfig().environment("TargetReach")
               .training(learning_starts=50)
               .env_runners(rollout_fragment_length=60).debugging(seed=0))
        algo = cfg.build_algo()
        algo.train()
        path = algo.save(str(tmp_path / "ck"))
        algo2 = cfg.copy().build_algo()
        algo2.restore(path)
        obs = np.array([0.5], np.float32)
        np.testing.assert_allclose(algo.compute_single_action(obs),
                                   algo2.compute_single_action(obs))


class TestOffline:
    def test_dataset_io(self, offline_dataset, tmp_path):
        data = OfflineData(offline_dataset)
        assert data.size == 4000
        assert set(data.columns) >= {"obs", "actions", "rewards",
                                     "next_obs", "terminateds",
                                     "returns_to_go"}
        batch = data.sample(32)
        assert batch["obs"].shape == (32, 4)
        # Glob loading across shards.
        import shutil
        shutil.copy(offline_dataset, tmp_path / "shard-1.npz")
        shutil.copy(offline_dataset, tmp_path / "shard-2.npz")
        multi = OfflineData(str(tmp_path / "shard-*.npz"))
        assert multi.size == 8000

    def test_bc_recovers_expert(self, offline_dataset):
        algo = (BCConfig().environment("StatelessGuess")
                .offline_data(input_path=offline_dataset,
                              updates_per_iteration=100)
                .training(lr=1e-2).debugging(seed=0)).build_algo()
        for _ in range(3):
            algo.train()
        assert _greedy_accuracy(algo) >= 95

    def test_marwil_recovers_expert(self, offline_dataset):
        algo = (MARWILConfig().environment("StatelessGuess")
                .offline_data(input_path=offline_dataset,
                              updates_per_iteration=100)
                .training(lr=1e-2, beta=1.0).debugging(seed=0)).build_algo()
        for _ in range(3):
            algo.train()
        assert _greedy_accuracy(algo) >= 95

    def test_cql_recovers_expert(self, offline_dataset):
        algo = (CQLConfig().environment("StatelessGuess")
                .offline_data(input_path=offline_dataset,
                              updates_per_iteration=100)
                .training(lr=1e-2, cql_alpha=0.5)
                .debugging(seed=0)).build_algo()
        for _ in range(3):
            r = algo.train()
        assert _greedy_accuracy(algo) >= 95
        # Conservative penalty is live (positive logsumexp gap).
        assert r["learner"]["cql_penalty"] >= 0.0

    def test_iql_recovers_expert(self, offline_dataset):
        from ray_tpu.rl import IQLConfig
        algo = (IQLConfig().environment("StatelessGuess")
                .offline_data(input_path=offline_dataset,
                              updates_per_iteration=100)
                .training(lr=1e-2, expectile=0.8, awr_beta=3.0)
                .debugging(seed=0)).build_algo()
        for _ in range(3):
            r = algo.train()
        assert _greedy_accuracy(algo) >= 95
        # The upper expectile keeps advantages spread around zero and the
        # AWR weights finite.
        assert np.isfinite(r["learner"]["adv_mean"])
        assert r["learner"]["w_mean"] > 0.0

    def test_parquet_roundtrip_through_data(self, ray_start, tmp_path):
        """Offline episodes written and read back THROUGH ray_tpu.data
        (reference: rllib offline_data.py reading parquet via Ray Data)."""
        from ray_tpu.rl import save_parquet
        rng = np.random.default_rng(0)
        cols = {
            "obs": rng.normal(size=(500, 4)).astype(np.float32),
            "actions": rng.integers(0, 4, 500),
            "rewards": rng.normal(size=500).astype(np.float32),
            "next_obs": rng.normal(size=(500, 4)).astype(np.float32),
            "terminateds": (rng.random(500) < 0.1).astype(np.float32),
        }
        out = str(tmp_path / "episodes")
        save_parquet(out, cols, shards=3)
        import glob as g
        assert len(g.glob(out + "/*.parquet")) >= 1
        data = OfflineData(out, seed=0)
        assert data.size == 500
        assert data.columns["obs"].shape == (500, 4)
        # Column contents survive the row-order-preserving round trip.
        np.testing.assert_allclose(data.columns["obs"], cols["obs"],
                                   rtol=1e-6)
        np.testing.assert_array_equal(data.columns["actions"],
                                      cols["actions"])
        b = data.sample(64)
        assert b["obs"].shape == (64, 4) and b["next_obs"].shape == (64, 4)

    def test_iql_on_parquet_dataset(self, ray_start, tmp_path):
        """End-to-end: collect behavior data to parquet via Data, train
        IQL from it."""
        from ray_tpu.rl import IQLConfig, collect_from_env
        out = str(tmp_path / "guess-episodes")

        def behavior(obs, rng):
            if rng.random() < 0.3:
                return int(rng.integers(4))
            return int(np.argmax(obs))

        collect_from_env("StatelessGuess", behavior, 3000, out, seed=1)
        algo = (IQLConfig().environment("StatelessGuess")
                .offline_data(input_path=out, updates_per_iteration=100)
                .training(lr=1e-2).debugging(seed=0)).build_algo()
        for _ in range(3):
            algo.train()
        assert _greedy_accuracy(algo) >= 90


class TestTQC:
    def test_learns_target_reach(self):
        from ray_tpu.rl import TQCConfig
        cfg = (TQCConfig().environment("TargetReach")
               .training(lr=3e-3, learning_starts=200, train_batch_size=64,
                         num_critics=2, num_quantiles=11,
                         top_quantiles_to_drop=2)
               .env_runners(rollout_fragment_length=200)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > -0.15
        errs = [abs(float(algo.compute_single_action(
            np.array([t], np.float32))[0]) - t)
            for t in np.linspace(-0.8, 0.8, 9)]
        assert max(errs) < 0.25


class TestMultiAgent:
    def test_independent_policies_learn(self):
        cfg = (MultiAgentPPOConfig()
               .environment(lambda: MultiGuess(seed=0))
               .multi_agent(policy_mapping_fn=lambda aid: aid)
               .training(lr=5e-3)
               .env_runners(rollout_fragment_length=256)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > 1.7
        assert set(algo.learners) == {"a0", "a1"}

    def test_shared_policy_learns(self):
        cfg = (MultiAgentPPOConfig()
               .environment(lambda: MultiGuess(seed=0))
               .multi_agent(policy_mapping_fn=lambda aid: "shared")
               .training(lr=5e-3)
               .env_runners(rollout_fragment_length=256)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > 1.7
        assert set(algo.learners) == {"shared"}


class TestConnectors:
    def test_meanstd_filter_stats(self):
        f = MeanStdFilter()
        rng = np.random.default_rng(0)
        data = rng.normal(5.0, 2.0, size=(200, 3)).astype(np.float32)
        for i in range(0, 200, 20):
            out = f(data[i:i + 20])
        # After enough samples the output is ~standardized.
        normed = f.transform(data)
        assert abs(float(normed.mean())) < 0.1
        assert abs(float(normed.std()) - 1.0) < 0.1
        # transform() does not advance the stats.
        n_before = f.count
        f.transform(data)
        assert f.count == n_before == 200

    def test_framestack_shapes_and_transform(self):
        fs = FrameStack(3)
        a = np.ones((2, 4), np.float32)
        out = fs(a)
        assert out.shape == (2, 12)
        b = 2 * np.ones((2, 4), np.float32)
        out2 = fs(b)
        # Newest frame last.
        assert out2[0, -1] == 2.0 and out2[0, 0] == 1.0
        # transform peeks without mutating.
        peek = fs.transform(3 * np.ones((2, 4), np.float32))
        assert peek[0, -1] == 3.0
        again = fs.transform(3 * np.ones((2, 4), np.float32))
        np.testing.assert_array_equal(peek, again)

    def test_framestack_clears_history_at_episode_boundary(self):
        fs = FrameStack(3)
        fs(np.ones((2, 2), np.float32))
        fs(2 * np.ones((2, 2), np.float32))
        # Sub-env 0 finished; its next obs is a fresh episode's reset state.
        fs.on_episode_boundaries(np.array([True, False]))
        out = fs(np.stack([7 * np.ones(2), 3 * np.ones(2)]).astype(
            np.float32))
        # Row 0: all frames replaced by the reset obs — no leak.
        np.testing.assert_array_equal(out[0], np.full(6, 7.0, np.float32))
        # Row 1: normal history [1, 2, 3].
        np.testing.assert_array_equal(
            out[1], np.array([1, 1, 2, 2, 3, 3], np.float32))

    def test_meanstd_merge_states(self):
        rng = np.random.default_rng(0)
        all_data = rng.normal(3.0, 1.5, size=(400, 2)).astype(np.float32)
        a, b = MeanStdFilter(), MeanStdFilter()
        a(all_data[:150])
        b(all_data[150:])
        merged = a.merge_states([a.get_state(), b.get_state()])
        whole = MeanStdFilter()
        whole(all_data)
        n, mean, m2 = merged["base"]
        wn, wmean, wm2 = whole._combined()
        assert n == wn == 400
        np.testing.assert_allclose(mean, wmean, rtol=1e-6)
        np.testing.assert_allclose(m2, wm2, rtol=1e-6)

    def test_meanstd_sync_does_not_double_count(self):
        """Sync round-trips must not re-count the shared base (the
        n ~ runners^iterations blowup)."""
        rng = np.random.default_rng(1)
        r1, r2 = MeanStdFilter(), MeanStdFilter()
        proto = MeanStdFilter()
        total = 0
        for _ in range(5):  # five sync rounds
            d1 = rng.normal(size=(30, 2)).astype(np.float32)
            d2 = rng.normal(size=(50, 2)).astype(np.float32)
            r1(d1)
            r2(d2)
            total += 80
            merged = proto.merge_states([r1.get_state(), r2.get_state()])
            r1.set_state(merged)
            r2.set_state(merged)
            assert r1.count == r2.count == total

    def test_state_sync_roundtrip(self):
        p1 = ConnectorPipeline([MeanStdFilter()])
        p1(np.arange(12, dtype=np.float32).reshape(4, 3))
        p2 = ConnectorPipeline([MeanStdFilter()])
        p2.set_state(p1.get_state())
        x = np.ones((1, 3), np.float32)
        np.testing.assert_allclose(p1.transform(x), p2.transform(x))

    def test_ppo_with_connectors_learns(self):
        cfg = (PPOConfig().environment("StatelessGuess")
               .env_runners(rollout_fragment_length=64,
                            env_to_module_connector=lambda: [MeanStdFilter()])
               .training(lr=5e-3).debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(12):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > 0.9


class TestIMPALA:
    def test_vtrace_on_policy_matches_returns(self):
        """With rho=c=1 and identical policies, vs == discounted returns
        under the value estimates (sanity anchor from the paper)."""
        T, N = 5, 2
        rng = np.random.default_rng(0)
        rewards = rng.normal(size=(T, N)).astype(np.float32)
        values = np.zeros((T, N), np.float32)
        logp = np.full((T, N), -0.5, np.float32)
        dones = np.zeros((T, N), bool)
        terms = np.zeros((T, N), bool)
        boot = np.zeros((T, N), np.float32)
        last = np.zeros(N, np.float32)
        vs, pg = vtrace(logp, logp, rewards, values, dones, terms, boot,
                        last, gamma=0.9)
        # With V=0 everywhere and no truncation, vs = discounted return.
        expect = np.zeros((T, N), np.float32)
        acc = np.zeros(N, np.float32)
        for t in reversed(range(T)):
            acc = rewards[t] + 0.9 * acc
            expect[t] = acc
        np.testing.assert_allclose(vs, expect, rtol=1e-5)

    def test_vtrace_terminated_stops_bootstrap(self):
        T, N = 3, 1
        rewards = np.ones((T, N), np.float32)
        values = np.full((T, N), 10.0, np.float32)
        logp = np.zeros((T, N), np.float32)
        dones = np.zeros((T, N), bool)
        terms = np.zeros((T, N), bool)
        dones[1, 0] = True
        terms[1, 0] = True
        boot = np.zeros((T, N), np.float32)
        last = np.full(N, 10.0, np.float32)
        vs, _ = vtrace(logp, logp, rewards, values, dones, terms, boot,
                       last, gamma=1.0, rho_clip=10.0, c_clip=10.0)
        # Step 1 is terminal: its target is exactly its reward.
        assert vs[1, 0] == pytest.approx(1.0)

    def test_sync_impala_learns(self):
        cfg = (IMPALAConfig().environment("StatelessGuess")
               .env_runners(num_env_runners=0, rollout_fragment_length=64)
               .training(lr=5e-3, batches_per_iteration=4)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > 0.9

    def test_appo_learns(self):
        from ray_tpu.rl import APPOConfig
        cfg = (APPOConfig().environment("StatelessGuess")
               .env_runners(num_env_runners=0, rollout_fragment_length=64)
               .training(lr=5e-3, batches_per_iteration=4, clip_param=0.2)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > 0.9

    def test_async_impala_learns(self, ray_start):
        cfg = (IMPALAConfig().environment("StatelessGuess")
               .env_runners(num_env_runners=2, rollout_fragment_length=64)
               .training(lr=5e-3, batches_per_iteration=4)
               .debugging(seed=0))
        algo = cfg.build_algo()
        for _ in range(10):
            r = algo.train()
        assert r["env_runners"]["episode_return_mean"] > 0.85
        algo.stop()


class TestModelZoo:
    """CNN + recurrent policies (reference analog: rllib/models vision
    and recurrent networks)."""

    def test_cnn_policy_shapes_and_learns_pattern(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.rl import CNNPolicyModule, CNNPolicySpec

        spec = CNNPolicySpec(obs_shape=(8, 8, 1), num_actions=2,
                             channels=(8, 16), hidden=32)
        mod = CNNPolicyModule(spec)
        params = mod.init(jax.random.key(0))
        # Pixel pattern: class = whether the bright quadrant is top-left.
        rng = np.random.default_rng(0)
        imgs = np.zeros((64, 8, 8, 1), np.float32)
        labels = rng.integers(0, 2, 64)
        for i, y in enumerate(labels):
            if y == 0:
                imgs[i, :4, :4, 0] = 1.0
            else:
                imgs[i, 4:, 4:, 0] = 1.0
        obs = jnp.asarray(imgs)
        lab = jnp.asarray(labels)
        out = mod.forward_train(params, obs)
        assert out["action_logits"].shape == (64, 2)
        assert out["value"].shape == (64,)

        def loss(p):
            lg = mod.forward_train(p, obs)["action_logits"]
            return -jnp.mean(jax.nn.log_softmax(lg)[jnp.arange(64), lab])

        step = jax.jit(jax.grad(loss))
        l0 = float(loss(params))
        for _ in range(60):
            g = step(params)
            params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
        assert float(loss(params)) < l0 * 0.2
        acc = float(jnp.mean(mod.forward_inference(params, obs) == lab))
        assert acc > 0.95

    def test_gru_train_matches_stepwise(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.rl import GRUPolicyModule, RecurrentPolicySpec

        spec = RecurrentPolicySpec(obs_dim=3, num_actions=4, hidden=8)
        mod = GRUPolicyModule(spec)
        params = mod.init(jax.random.key(1))
        rng = np.random.default_rng(1)
        obs_seq = jnp.asarray(rng.normal(size=(2, 5, 3)).astype(np.float32))
        h0 = mod.initial_state(2)
        out = mod.forward_train(params, obs_seq, h0)
        logits_tr, values_tr = out["action_logits"], out["value"]
        assert logits_tr.shape == (2, 5, 4) and values_tr.shape == (2, 5)
        # Step-by-step unroll must agree with the scanned training pass.
        h = h0
        for t in range(5):
            lg, v, h = mod.forward_step(params, obs_seq[:, t], h)
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(logits_tr[:, t]),
                                       rtol=1e-5, atol=1e-5)

    def test_gru_uses_memory(self):
        """The recurrent core must beat a memoryless readout on a task
        where the answer is the FIRST observation of the sequence."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.rl import GRUPolicyModule, RecurrentPolicySpec

        spec = RecurrentPolicySpec(obs_dim=2, num_actions=2, hidden=16)
        mod = GRUPolicyModule(spec)
        params = mod.init(jax.random.key(2))
        rng = np.random.default_rng(2)
        first = rng.integers(0, 2, 64)
        seqs = np.zeros((64, 6, 2), np.float32)
        seqs[np.arange(64), 0, first] = 1.0  # signal only at t=0
        obs = jnp.asarray(seqs)
        lab = jnp.asarray(first)

        def loss(p):
            lg = mod.forward_train(p, obs,
                                   mod.initial_state(64))["action_logits"]
            return -jnp.mean(
                jax.nn.log_softmax(lg[:, -1])[jnp.arange(64), lab])

        step = jax.jit(jax.grad(loss))
        for _ in range(150):
            g = step(params)
            params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
        # Predicting t=0's signal at t=5 requires carrying state.
        assert float(loss(params)) < 0.1


class TestJaxVectorEnv:
    def test_dynamics_match_python_env(self):
        import jax.numpy as jnp
        import numpy as np
        from ray_tpu.rl import CartPole
        from ray_tpu.rl.jax_env import JaxCartPoleVector

        vec = JaxCartPoleVector(num_envs=4, seed=3)
        obs = np.asarray(vec.reset())
        py = CartPole()
        py._state = obs[1].astype(np.float64)
        py._t = 0
        actions = np.array([0, 1, 0, 1])
        nxt, rew, term, trunc = vec.step(jnp.asarray(actions))
        want, r, term, trunc, _ = py.step(int(actions[1]))
        np.testing.assert_allclose(np.asarray(nxt)[1], want, rtol=1e-5,
                                   atol=1e-6)
        assert float(rew[1]) == r

    def test_fused_rollout_collects_batches(self):
        import jax
        import jax.numpy as jnp
        from ray_tpu.rl.jax_env import JaxCartPoleVector

        n, steps = 256, 50
        vec = JaxCartPoleVector(num_envs=n, seed=4)
        vec.reset()

        def random_policy(_params, obs, key):
            return jax.random.randint(key, (obs.shape[0],), 0, 2)

        obs, actions, rewards, terms, truncs = vec.rollout(
            None, random_policy, steps, jax.random.key(0))
        assert obs.shape == (steps, n, 4)
        assert actions.shape == (steps, n)
        assert float(rewards.sum()) == steps * n  # +1 every step
        # Random policy on cartpole terminates episodes within 50 steps.
        assert bool(terms.any())
        assert not bool(truncs.any())  # max_steps=500 never hit in 50


class TestEnvRunnerHooks:
    def test_custom_module_and_reward_connector(self):
        import numpy as np
        from ray_tpu.rl import (CartPole, DiscretePolicyModule,
                                EnvRunner, RewardClip, RLModuleSpec)

        spec = RLModuleSpec(4, 2, hidden=(8,))
        runner = EnvRunner(lambda: CartPole(max_steps=20), num_envs=2,
                           module_spec=spec,
                           module=DiscretePolicyModule(spec),
                           reward_connector=RewardClip(0.5))
        batch = runner.sample(num_steps=10)
        assert batch["rewards"].shape == (10, 2)
        # CartPole rewards are +1; the reward-path connector clipped them.
        assert np.all(batch["rewards"] == 0.5)


class TestRecurrentPPO:
    """GRU-PPO through the FULL Algorithm/EnvRunner/Learner stack
    (reference: rllib recurrent modules through
    env/single_agent_env_runner.py:66 + sequence-batched PPO)."""

    def _train(self, module_factory, iters, seed=0):
        from ray_tpu.rl import PPOConfig
        from ray_tpu.rl.env import DelayedRecall

        cfg = (PPOConfig()
               .environment(lambda: DelayedRecall(delay=3))
               .env_runners(num_envs_per_env_runner=16,
                            rollout_fragment_length=32)
               .training(lr=5e-3, num_epochs=6, minibatch_size=256,
                         gamma=0.9, entropy_coeff=0.003)
               .debugging(seed=seed))
        if module_factory is not None:
            cfg = cfg.rl_module(module_factory=module_factory)
        algo = cfg.build_algo()
        try:
            last = None
            for _ in range(iters):
                last = algo.train()
            return last["env_runners"]["episode_return_mean"]
        finally:
            algo.stop()

    def test_gru_ppo_beats_memoryless_on_memory_task(self, ray_start):
        """DelayedRecall pays only for remembering the first
        observation: the memoryless MLP is capped at ~1/2 expected
        return; the GRU module through the same stack must clearly beat
        it."""
        from ray_tpu.rl import GRUPolicyModule, RecurrentPolicySpec

        def gru_factory():
            return GRUPolicyModule(RecurrentPolicySpec(
                obs_dim=3, num_actions=2, hidden=16, embed=(32,)))

        ret_gru = self._train(gru_factory, iters=25)
        ret_mlp = self._train(None, iters=25)
        assert ret_mlp < 0.75, f"memoryless should be capped: {ret_mlp}"
        assert ret_gru > 0.85, f"GRU-PPO failed to learn: {ret_gru}"
        assert ret_gru > ret_mlp + 0.15
