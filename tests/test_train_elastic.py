"""Elastic training tests: resize-on-failure and upsize-on-capacity over a
multi-node cluster (reference analogs: train/v2 elastic scaling policy
scaling_policy/elastic.py + release/train_tests/elastic_training, and
test_jax_elastic_e2e.py)."""

from __future__ import annotations

import tempfile
import threading
import time

import pytest

from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (JaxTrainer, RunConfig, FailureConfig,
                           ScalingConfig)


def make_train_fn(total_steps: int, step_time: float):
    def train_fn(config=None):
        import os
        import tempfile as _tf
        import time as _time

        import ray_tpu.train as train

        ctx = train.get_context()
        rank = ctx.get_world_rank()
        world = ctx.get_world_size()
        start = 0
        ckpt = ctx.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "step.txt")) as f:
                start = int(f.read())
        for step in range(start, total_steps):
            _time.sleep(step_time)
            if rank == 0:
                d = _tf.mkdtemp(prefix="elastic_ck_")
                with open(os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step + 1))
                train.report({"step": step + 1, "start": start,
                              "world": world},
                             checkpoint=train.Checkpoint(d))
            else:
                train.report({"step": step + 1, "start": start,
                              "world": world})
    return train_fn


@pytest.fixture()
def cluster(monkeypatch):
    # Elastic failover tests assert on PROMPT node-death handling; the
    # reconnect grace window (node_reconnect_grace_s, test_reconnect.py)
    # would let the collective-free toy train fn run to completion before
    # the death fan-out fires, changing what the assertions measure.
    monkeypatch.setenv("RAY_TPU_NODE_RECONNECT_GRACE_S", "0")
    c = Cluster(head_num_cpus=0)  # init re-resolves Config from env
    yield c
    c.shutdown()


class TestElasticTrain:
    def test_downscale_after_node_death(self, cluster):
        n1 = cluster.add_node(num_cpus=2)
        n2 = cluster.add_node(num_cpus=2)
        trainer = JaxTrainer(
            make_train_fn(total_steps=14, step_time=0.4),
            scaling_config=ScalingConfig(
                resources_per_worker={"CPU": 1},
                min_workers=1, max_workers=4,
                elastic_check_interval_s=3600,  # no upsize in this test
                env_per_worker={"JAX_PLATFORMS": "cpu",
                                "PALLAS_AXON_POOL_IPS": "",
                                "XLA_FLAGS": ""}),
            run_config=RunConfig(
                storage_path=tempfile.mkdtemp(prefix="elastic_"),
                failure_config=FailureConfig(max_failures=3)))

        killed = {"done": False}

        def killer():
            # Wait until training reported progress, then take a node down.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if any(r["metrics"].get("step", 0) >= 2
                       for r in trainer_result_probe()):
                    break
                time.sleep(0.2)
            cluster.remove_node(n2)
            killed["done"] = True

        controller_holder = {}

        def trainer_result_probe():
            c = controller_holder.get("c")
            return c._reports if c is not None else []

        # Run fit() on a thread so the test can inject the node death.
        from ray_tpu.train.controller import TrainController
        controller = TrainController(
            trainer._train_fn, trainer._config, trainer._scaling,
            trainer._run_config)
        controller_holder["c"] = controller
        result_box = {}

        def run():
            import ray_tpu
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            result_box["r"] = controller.run()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        t.join(timeout=240)
        assert not t.is_alive(), "training did not finish"
        r = result_box["r"]
        assert r.error is None
        assert killed["done"]
        # First incarnation used all 4 slots; post-death incarnation 2.
        assert r.world_size_history[0] == 4
        assert r.world_size_history[-1] == 2
        assert r.metrics["step"] == 14
        # The restart resumed from a checkpoint, not step 0.
        assert r.metrics["start"] > 0

    def test_upscale_when_capacity_appears(self, cluster):
        cluster.add_node(num_cpus=2)
        from ray_tpu.train.controller import TrainController
        trainer = JaxTrainer(
            make_train_fn(total_steps=12, step_time=0.5),
            scaling_config=ScalingConfig(
                resources_per_worker={"CPU": 1},
                min_workers=1, max_workers=4,
                elastic_check_interval_s=1.0,
                env_per_worker={"JAX_PLATFORMS": "cpu",
                                "PALLAS_AXON_POOL_IPS": "",
                                "XLA_FLAGS": ""}),
            run_config=RunConfig(
                storage_path=tempfile.mkdtemp(prefix="elastic_"),
                failure_config=FailureConfig(max_failures=2)))
        controller = TrainController(
            trainer._train_fn, trainer._config, trainer._scaling,
            trainer._run_config)
        result_box = {}

        def run():
            import ray_tpu
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            result_box["r"] = controller.run()

        t = threading.Thread(target=run, daemon=True)
        t.start()

        def grower():
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if any(r["metrics"].get("step", 0) >= 2
                       for r in controller._reports):
                    break
                time.sleep(0.2)
            cluster.add_node(num_cpus=2)

        g = threading.Thread(target=grower, daemon=True)
        g.start()
        t.join(timeout=240)
        assert not t.is_alive(), "training did not finish"
        r = result_box["r"]
        assert r.error is None
        assert r.world_size_history[0] == 2
        assert max(r.world_size_history) == 4  # upsized mid-run
        assert r.metrics["step"] == 12
