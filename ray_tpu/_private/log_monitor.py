"""Session directory, worker log redirection + tailing, export events.

Reference analogs: per-process log files under /tmp/ray/session_*/logs
tailed by the LogMonitor (python/ray/_private/log_monitor.py:116) and
republished to the driver; structured export events (export_*.proto +
RayEventRecorder, src/ray/observability/ray_event_recorder.h:36) written
for external consumers.

Here: each worker's stdout/stderr is redirected to
``<session>/logs/worker-<id>.out|.err``; a driver-side LogMonitor thread
tails the directory and echoes fresh lines prefixed ``(worker-xxxxxxx
.err)`` — the reference's "(pid=...) ..." stream — while keeping the files
for the state API (``ctl_log_tail``).  Export events are JSON lines in
``<session>/logs/events.jsonl``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .config import Config


def create_session_dir() -> str:
    base = Config.get("session_dir") or "/tmp/ray_tpu"
    path = os.path.join(
        base, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{os.getpid()}")
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    # Convenience symlink like the reference's session_latest.
    link = os.path.join(base, "session_latest")
    try:
        if os.path.islink(link) or os.path.exists(link):
            os.remove(link)
        os.symlink(path, link)
    except OSError:
        pass
    return path


class LogMonitor:
    """Tails every log file in a directory, emitting new lines.

    Reference: _private/log_monitor.py:116 — there the tail is pushed
    through GCS pubsub to drivers; here the monitor runs in the driver
    process itself, so it just writes to the driver's stderr.
    """

    def __init__(self, logs_dir: str,
                 emit: Optional[Callable[[str, str], None]] = None):
        self.logs_dir = logs_dir
        self._emit = emit or self._default_emit
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._period = Config.get("log_monitor_poll_ms") / 1000.0

    @staticmethod
    def _default_emit(fname: str, line: str) -> None:
        tag = fname.rsplit(".", 1)[0]
        stream = sys.stderr if fname.endswith(".err") else sys.stdout
        print(f"({tag}) {line}", file=stream)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="log-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # Join so a caller's post-stop flush poll cannot race an in-flight
        # poll (duplicate emission / concurrent offset writes); the loop
        # waits on a 200ms event, so this returns promptly.
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — monitor must never die
                pass

    def poll_once(self) -> int:
        """Scan files once; returns number of lines emitted."""
        emitted = 0
        try:
            names = sorted(os.listdir(self.logs_dir))
        except OSError:
            return 0
        for fname in names:
            if not (fname.endswith(".out") or fname.endswith(".err")):
                continue
            path = os.path.join(self.logs_dir, fname)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(fname, 0)
            if size <= off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            # Only emit complete lines; keep the partial tail for later.
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue
            self._offsets[fname] = off + last_nl + 1
            for raw in chunk[:last_nl].split(b"\n"):
                line = raw.decode("utf-8", "replace").rstrip("\r")
                if line:
                    self._emit(fname, line)
                    emitted += 1
        return emitted

    def tail(self, fname: str, n: int = 100) -> List[str]:
        """Last n lines of one log file (state-API surface)."""
        path = os.path.join(self.logs_dir, os.path.basename(fname))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                data = f.read()
        except OSError:
            return []
        lines = data.decode("utf-8", "replace").splitlines()
        return lines[-n:]

    def list_files(self) -> List[Tuple[str, int]]:
        try:
            return sorted(
                (f, os.path.getsize(os.path.join(self.logs_dir, f)))
                for f in os.listdir(self.logs_dir))
        except OSError:
            return []


class ExportEventWriter:
    """Append-only JSONL of structured lifecycle events (reference:
    export_*.proto events recorded by RayEventRecorder for external
    pipelines)."""

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)

    def write(self, source_type: str, event: Dict[str, Any]) -> None:
        rec = {"timestamp": time.time(), "source_type": source_type,
               **event}
        try:
            with self._lock:
                self._f.write(json.dumps(rec, default=str) + "\n")
        except ValueError:
            pass  # closed during shutdown race

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except Exception:  # noqa: BLE001
                pass
